"""CSV file connector.

Reference role: the file-format storage connectors (lib/trino-hive-formats
text codecs + the hive connector's table mapping). Minimal file-based
connector: a root directory, schemas as subdirectories, tables as
`<name>.csv` files with a header row. Types are inferred column-wise
(BIGINT -> DOUBLE -> DATE -> VARCHAR); empty cells are NULL; VARCHAR
columns dictionary-encode at load (the engine's ingest policy — strings
never reach the device).

    catalog.register("csv", CsvConnector("/data"))
    SELECT * FROM csv.default.mytable
"""

from __future__ import annotations

import csv
import datetime
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import Field, Schema
from ..types import BIGINT, DATE, DOUBLE, VARCHAR
from .tpch.datagen import TableData

EPOCH = datetime.date(1970, 1, 1)


def _infer(values: List[str]):
    """Column type from non-empty cells: BIGINT | DOUBLE | DATE | VARCHAR."""
    kinds = {"int": True, "float": True, "date": True}
    seen = False
    for v in values:
        if v == "":
            continue
        seen = True
        if kinds["int"]:
            try:
                int(v)
            except ValueError:
                kinds["int"] = False
        if not kinds["int"] and kinds["float"]:
            try:
                float(v)
            except ValueError:
                kinds["float"] = False
        if kinds["date"]:
            try:
                datetime.date.fromisoformat(v)
            except ValueError:
                kinds["date"] = False
    if not seen:
        return VARCHAR
    if kinds["int"]:
        return BIGINT
    if kinds["float"]:
        return DOUBLE
    if kinds["date"]:
        return DATE
    return VARCHAR


def load_csv(path: str, name: str) -> TableData:
    with open(path, newline="") as f:
        reader = csv.reader(f)
        rows = list(reader)
    if not rows:
        raise ValueError(f"{path}: empty CSV (need a header row)")
    header, body = rows[0], rows[1:]
    ncols = len(header)
    columns = [[r[i] if i < len(r) else "" for r in body]
               for i in range(ncols)]
    fields: List[Field] = []
    arrays: List[np.ndarray] = []
    valids: List[Optional[np.ndarray]] = []
    for cname, cells in zip(header, columns):
        dtype = _infer(cells)
        valid = np.array([c != "" for c in cells], dtype=np.bool_)
        if dtype is BIGINT:
            arrays.append(np.array([int(c) if c else 0 for c in cells],
                                   dtype=np.int64))
            fields.append(Field(cname, BIGINT))
        elif dtype is DOUBLE:
            arrays.append(np.array([float(c) if c else 0.0 for c in cells],
                                   dtype=np.float64))
            fields.append(Field(cname, DOUBLE))
        elif dtype is DATE:
            arrays.append(np.array(
                [(datetime.date.fromisoformat(c) - EPOCH).days if c else 0
                 for c in cells], dtype=np.int32))
            fields.append(Field(cname, DATE))
        else:
            pool = sorted({c for c, v in zip(cells, valid) if v})
            index = {s: i for i, s in enumerate(pool)}
            arrays.append(np.array([index.get(c, 0) for c in cells],
                                   dtype=np.int32))
            fields.append(Field(cname, VARCHAR, dictionary=tuple(pool)))
        valids.append(None if valid.all() else valid)
    if all(v is None for v in valids):
        valids = None
    return TableData(name, Schema(tuple(fields)), arrays, valids=valids)


class CsvConnector:
    name = "csv"

    def __init__(self, root: str):
        self.root = root
        self._cache: Dict[Tuple[str, str], TableData] = {}

    def _schema_dir(self, schema: str) -> str:
        return os.path.join(self.root, schema)

    def schema_names(self):
        if not os.path.isdir(self.root):
            return []
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, d)))

    def table_names(self, schema: str):
        d = self._schema_dir(schema)
        if not os.path.isdir(d):
            return []
        return sorted(f[:-4] for f in os.listdir(d) if f.endswith(".csv"))

    def get_table(self, schema: str, table: str) -> TableData:
        key = (schema, table)
        if key not in self._cache:
            path = os.path.join(self._schema_dir(schema), f"{table}.csv")
            if not os.path.isfile(path):
                raise KeyError(f"csv table {schema}.{table} not found "
                               f"({path})")
            self._cache[key] = load_csv(path, table)
        return self._cache[key]
