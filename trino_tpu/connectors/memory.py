"""In-memory connector (reference: plugin/trino-memory, MemoryMetadata/
MemoryPagesStore) — tables created via CREATE TABLE / CTAS / INSERT or
programmatically, held as host numpy columns."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import Field, Schema
from ..types import TypeKind
from .tpch.datagen import TableData


def _remap_codes(target_field: Field, src_field: Optional[Field],
                 codes: np.ndarray):
    """Translate VARCHAR codes from `src_field`'s pool into
    `target_field`'s, extending the target pool with unseen strings while
    KEEPING THE POOL SORTED — the engine-wide invariant that code order ==
    string order (varchar range compares, ORDER BY, min/max all rely on
    it), so unseen strings INSERT at their sorted position rather than
    append. That can renumber existing codes, so the remap for the
    STORED column's codes is returned too.

    Returns (remapped incoming codes, remap array for existing stored
    codes or None if their numbering is unchanged, updated Field)."""
    old_pool = tuple(target_field.dictionary or ())
    src_pool = tuple(src_field.dictionary or ()) if src_field else ()
    merged = tuple(sorted(set(old_pool) | set(src_pool)))
    index = {s: j for j, s in enumerate(merged)}
    src_remap = np.array([index[s] for s in src_pool] or [0],
                         dtype=np.int32)
    old_remap = None
    if merged != old_pool and old_pool:
        old_remap = np.array([index[s] for s in old_pool],
                             dtype=np.int32)
    new_codes = src_remap[np.clip(np.asarray(codes, dtype=np.int32),
                                  0, len(src_remap) - 1)]
    return new_codes, old_remap, Field(
        target_field.name, target_field.dtype, dictionary=merged)


def _apply_old_remap(old_codes: np.ndarray,
                     old_remap: Optional[np.ndarray]) -> np.ndarray:
    if old_remap is None or len(old_codes) == 0:
        return old_codes
    return old_remap[np.clip(np.asarray(old_codes, dtype=np.int32),
                             0, len(old_remap) - 1)]


class MemoryConnector:
    name = "memory"

    def __init__(self):
        self._tables: Dict[Tuple[str, str], TableData] = {}

    @staticmethod
    def _note_zones(data: TableData) -> None:
        """Eager insert-time zone maps (scans of file/generator tables
        build theirs lazily). Every mutation stores a NEW TableData, so
        noting it here also retires the previous version's zones."""
        try:
            from ..exec.zonemap import note_table
            note_table(data)
        except Exception:   # noqa: BLE001 — pruning is advisory only
            pass

    def schema_names(self):
        return sorted({s for (s, _) in self._tables}) or ["default"]

    def table_names(self, schema: str):
        return sorted(t for (s, t) in self._tables if s == schema)

    def create_table(self, schema: str, name: str, data: TableData,
                     if_not_exists: bool = False) -> None:
        key = (schema, name)
        if key in self._tables:
            if if_not_exists:
                return
            raise KeyError(f"table {schema}.{name} already exists")
        self._tables[key] = data
        self._note_zones(data)

    def drop_table(self, schema: str, name: str,
                   if_exists: bool = False) -> None:
        key = (schema, name)
        if key not in self._tables:
            if if_exists:
                return
            raise KeyError(f"memory table {schema}.{name} not found")
        del self._tables[key]

    def insert(self, schema: str, name: str, arrays: List[np.ndarray],
               valids: List[Optional[np.ndarray]],
               fields: List[Field]) -> int:
        """Append rows (ConnectorPageSink.appendPage's role). VARCHAR
        columns arrive as codes + their pool in `fields`; they are remapped
        into the stored table's pool, extending it with unseen strings."""
        key = (schema, name)
        if key not in self._tables:
            raise KeyError(f"memory table {schema}.{name} not found")
        t = self._tables[key]
        if len(arrays) != len(t.schema.fields):
            raise ValueError(
                f"INSERT has {len(arrays)} columns, table has "
                f"{len(t.schema.fields)}")
        new_cols = []
        new_fields = []
        new_valids = []
        for i, (tf, nf) in enumerate(zip(t.schema.fields, fields)):
            old = np.asarray(t.columns[i])
            add = np.asarray(arrays[i])
            fld = tf
            if tf.dtype.kind is TypeKind.VARCHAR:
                add, old_remap, fld = _remap_codes(tf, nf, add)
                old = _apply_old_remap(old, old_remap)
            elif add.dtype != old.dtype:
                add = add.astype(old.dtype)
            new_cols.append(np.concatenate([old, add]))
            new_fields.append(fld)
            ov = None if t.valids is None else t.valids[i]
            if ov is None:
                ov = np.ones(len(old), dtype=np.bool_)
            nv = valids[i]
            if nv is None:
                nv = np.ones(len(add), dtype=np.bool_)
            new_valids.append(np.concatenate([np.asarray(ov),
                                              np.asarray(nv)]))
        self._tables[key] = TableData(
            t.name, Schema(tuple(new_fields)), new_cols,
            primary_key=(), valids=new_valids)
        self._note_zones(self._tables[key])
        return len(arrays[0]) if arrays else 0

    def get_table(self, schema: str, table: str) -> TableData:
        key = (schema, table)
        if key not in self._tables:
            raise KeyError(f"memory table {schema}.{table} not found")
        return self._tables[key]

    # ---- mutation (the MergeWriterOperator / row-change tier) -----------

    def delete_rows(self, schema: str, name: str,
                    ids: np.ndarray) -> int:
        """Drop rows by position (row-id + delete-mask scheme, the
        reference's row-change paradigm reduced to the in-memory case)."""
        t = self.get_table(schema, name)
        keep = np.ones(t.num_rows, dtype=np.bool_)
        keep[np.asarray(ids, dtype=np.int64)] = False
        cols = [np.asarray(c)[keep] for c in t.columns]
        valids = None
        if t.valids is not None:
            valids = [None if v is None else np.asarray(v)[keep]
                      for v in t.valids]
        self._tables[(schema, name)] = TableData(
            t.name, t.schema, cols, primary_key=(), valids=valids)
        return int((~keep).sum())

    def update_rows(self, schema: str, name: str, ids: np.ndarray,
                    updates: dict) -> int:
        """Overwrite columns at row positions. `updates` maps column name
        -> (values, valid, field); VARCHAR values arrive as codes in the
        field's pool and are remapped into (and extend) the stored
        pool."""
        t = self.get_table(schema, name)
        ids = np.asarray(ids, dtype=np.int64)
        cols = [np.asarray(c).copy() for c in t.columns]
        valids = [np.ones(t.num_rows, dtype=np.bool_)
                  if t.valids is None or t.valids[i] is None
                  else np.asarray(t.valids[i]).copy()
                  for i in range(len(cols))]
        fields = list(t.schema.fields)
        for col_name, (vals, valid, src_field) in updates.items():
            i = t.schema.index_of(col_name)
            tf = fields[i]
            vals = np.asarray(vals)
            if tf.dtype.kind is TypeKind.VARCHAR:
                vals, old_remap, fields[i] = _remap_codes(tf, src_field,
                                                          vals)
                cols[i] = _apply_old_remap(cols[i], old_remap)
            else:
                vals = vals.astype(cols[i].dtype)
            cols[i][ids] = vals
            valids[i][ids] = np.ones(len(ids), dtype=np.bool_) \
                if valid is None else np.asarray(valid)
        self._tables[(schema, name)] = TableData(
            t.name, Schema(tuple(fields)), cols, primary_key=(),
            valids=valids)
        return len(ids)
