"""In-memory connector (reference: plugin/trino-memory, MemoryMetadata/
MemoryPagesStore) — tables created via CREATE TABLE / CTAS / INSERT or
programmatically, held as host numpy columns."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import Field, Schema
from ..types import TypeKind
from .tpch.datagen import TableData


class MemoryConnector:
    name = "memory"

    def __init__(self):
        self._tables: Dict[Tuple[str, str], TableData] = {}

    def schema_names(self):
        return sorted({s for (s, _) in self._tables}) or ["default"]

    def table_names(self, schema: str):
        return sorted(t for (s, t) in self._tables if s == schema)

    def create_table(self, schema: str, name: str, data: TableData,
                     if_not_exists: bool = False) -> None:
        key = (schema, name)
        if key in self._tables:
            if if_not_exists:
                return
            raise KeyError(f"table {schema}.{name} already exists")
        self._tables[key] = data

    def drop_table(self, schema: str, name: str,
                   if_exists: bool = False) -> None:
        key = (schema, name)
        if key not in self._tables:
            if if_exists:
                return
            raise KeyError(f"memory table {schema}.{name} not found")
        del self._tables[key]

    def insert(self, schema: str, name: str, arrays: List[np.ndarray],
               valids: List[Optional[np.ndarray]],
               fields: List[Field]) -> int:
        """Append rows (ConnectorPageSink.appendPage's role). VARCHAR
        columns arrive as codes + their pool in `fields`; they are remapped
        into the stored table's pool, extending it with unseen strings."""
        key = (schema, name)
        if key not in self._tables:
            raise KeyError(f"memory table {schema}.{name} not found")
        t = self._tables[key]
        if len(arrays) != len(t.schema.fields):
            raise ValueError(
                f"INSERT has {len(arrays)} columns, table has "
                f"{len(t.schema.fields)}")
        new_cols = []
        new_fields = []
        new_valids = []
        for i, (tf, nf) in enumerate(zip(t.schema.fields, fields)):
            old = np.asarray(t.columns[i])
            add = np.asarray(arrays[i])
            fld = tf
            if tf.dtype.kind is TypeKind.VARCHAR:
                pool = list(tf.dictionary or ())
                index = {s: j for j, s in enumerate(pool)}
                src_pool = nf.dictionary or ()
                remap = np.zeros(max(len(src_pool), 1), dtype=np.int32)
                for j, s in enumerate(src_pool):
                    if s not in index:
                        index[s] = len(pool)
                        pool.append(s)
                    remap[j] = index[s]
                add = remap[add.astype(np.int32)]
                fld = Field(tf.name, tf.dtype, dictionary=tuple(pool))
            elif add.dtype != old.dtype:
                add = add.astype(old.dtype)
            new_cols.append(np.concatenate([old, add]))
            new_fields.append(fld)
            ov = None if t.valids is None else t.valids[i]
            if ov is None:
                ov = np.ones(len(old), dtype=np.bool_)
            nv = valids[i]
            if nv is None:
                nv = np.ones(len(add), dtype=np.bool_)
            new_valids.append(np.concatenate([np.asarray(ov),
                                              np.asarray(nv)]))
        self._tables[key] = TableData(
            t.name, Schema(tuple(new_fields)), new_cols,
            primary_key=(), valids=new_valids)
        return len(arrays[0]) if arrays else 0

    def get_table(self, schema: str, table: str) -> TableData:
        key = (schema, table)
        if key not in self._tables:
            raise KeyError(f"memory table {schema}.{table} not found")
        return self._tables[key]
