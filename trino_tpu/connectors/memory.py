"""In-memory connector (reference: plugin/trino-memory, MemoryMetadata/
MemoryPagesStore) — tables created programmatically or via INSERT, held as
host numpy columns."""

from __future__ import annotations

from typing import Dict, Tuple

from .tpch.datagen import TableData


class MemoryConnector:
    name = "memory"

    def __init__(self):
        self._tables: Dict[Tuple[str, str], TableData] = {}

    def schema_names(self):
        return sorted({s for (s, _) in self._tables})

    def table_names(self, schema: str):
        return sorted(t for (s, t) in self._tables if s == schema)

    def create_table(self, schema: str, name: str, data: TableData) -> None:
        self._tables[(schema, name)] = data

    def get_table(self, schema: str, table: str) -> TableData:
        key = (schema, table)
        if key not in self._tables:
            raise KeyError(f"memory table {schema}.{table} not found")
        return self._tables[key]
