"""On-disk cache for generated connector tables.

Reference analog: the benchto methodology benchmarks Trino over
pre-generated ORC/Parquet data on disk (testing/trino-benchto-benchmarks),
not over in-process generation — datagen cost is paid once per dataset,
not once per run.  Here a generated TableData is persisted as one .npy per
column plus a JSON sidecar (schema, dictionaries, primary key); loads are
np.load(mmap_mode='r'), so a bench restart reads pages lazily from the OS
cache instead of re-running minutes of dbgen formulas.

Layout: {root}/{dataset}/{table}/meta.json + col{i}.npy + valid{i}.npy.
Default root: $TRINO_TPU_DATA_CACHE or <repo>/.datacache (gitignored).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

import numpy as np


def cache_root() -> str:
    env = os.environ.get("TRINO_TPU_DATA_CACHE")
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".datacache")


def _type_to_json(dt) -> dict:
    out = {"kind": dt.kind.value}
    if dt.precision is not None:
        out["precision"] = dt.precision
    if dt.scale is not None:
        out["scale"] = dt.scale
    if dt.element is not None:
        out["element"] = _type_to_json(dt.element)
    return out


def _type_from_json(d):
    from ..types import DataType, TypeKind
    return DataType(TypeKind(d["kind"]), d.get("precision"),
                    d.get("scale"),
                    _type_from_json(d["element"]) if "element" in d
                    else None)


def save_table(dataset: str, table) -> None:
    """Persist one TableData. Atomic per table (tmp dir + rename) so a
    killed bench never leaves a half-written table behind."""
    from ..batch import Field  # noqa: F401 — layout documented above
    root = os.path.join(cache_root(), dataset)
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, table.name)
    if os.path.isdir(final):
        return
    tmp = tempfile.mkdtemp(dir=root, prefix=f".{table.name}.")
    try:
        meta = {
            "name": table.name,
            "primary_key": list(table.primary_key),
            "fields": [{"name": f.name, "dtype": _type_to_json(f.dtype),
                        "dictionary": list(f.dictionary)
                        if f.dictionary is not None else None}
                       for f in table.schema.fields],
            "valids": [v is not None for v in table.valids]
            if table.valids is not None else None,
        }
        for i, col in enumerate(table.columns):
            np.save(os.path.join(tmp, f"col{i}.npy"),
                    np.ascontiguousarray(col))
        if table.valids is not None:
            for i, v in enumerate(table.valids):
                if v is not None:
                    np.save(os.path.join(tmp, f"valid{i}.npy"),
                            np.ascontiguousarray(v))
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        os.rename(tmp, final)
    except OSError:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


def get_or_generate(dataset: str, table: str, mem_cache: dict,
                    generate_fn, table_cls, use_disk: bool):
    """Connector-side cache protocol shared by tpch/tpcds: in-memory dict
    first, then disk, then whole-schema generation (persisting every
    generated table when use_disk)."""
    if table not in mem_cache:
        if use_disk:
            t = load_table(dataset, table, table_cls)
            if t is not None:
                mem_cache[table] = t
                return t
        generated = generate_fn()
        if use_disk:
            for t in generated.values():
                save_table(dataset, t)
        mem_cache.update(generated)
    return mem_cache[table]


def load_table(dataset: str, name: str, table_cls) -> Optional[object]:
    """Load one table back as `table_cls` (TableData-shaped), or None."""
    from ..batch import Field, Schema
    d = os.path.join(cache_root(), dataset, name)
    meta_path = os.path.join(d, "meta.json")
    if not os.path.isfile(meta_path):
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    fields = tuple(
        Field(fm["name"], _type_from_json(fm["dtype"]),
              tuple(fm["dictionary"]) if fm["dictionary"] is not None
              else None)
        for fm in meta["fields"])
    columns = [np.load(os.path.join(d, f"col{i}.npy"), mmap_mode="r")
               for i in range(len(fields))]
    valids = None
    if meta["valids"] is not None:
        valids = [np.load(os.path.join(d, f"valid{i}.npy"), mmap_mode="r")
                  if has else None
                  for i, has in enumerate(meta["valids"])]
    return table_cls(meta["name"], Schema(fields), columns,
                     primary_key=tuple(meta["primary_key"]), valids=valids)
