"""Deterministic in-memory TPC-DS data generator.

Role of the reference's ``plugin/trino-tpcds`` connector (backed by the
Teradata tpcds row generators, TpcdsRecordSet): a deterministic benchmark
source needing no files. Schemas follow the TPC-DS specification's table
definitions (surrogate-key star schema: date_dim/item/customer/... dimension
tables around store_sales/catalog_sales/web_sales/store_returns facts);
value distributions are seeded-random rather than dsdgen-exact. Correctness
testing always runs the sqlite oracle on *this* generated data (the
H2QueryRunner pattern, SURVEY.md §4.4), so engine results are verified
end-to-end regardless of distribution fidelity.

Facts carry NULL foreign keys at ~4% (dsdgen also nulls fact FKs), so
benchmark queries exercise three-valued logic and join NULL semantics.

Decimals are scaled int64 at scale 2.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional

import numpy as np

from ...batch import Field, Schema
from ...types import BIGINT, DATE, INTEGER, VARCHAR, decimal
from ..tpch.datagen import TableData, _codes_for, _dict_field

D72 = decimal(7, 2)

EPOCH = datetime.date(1970, 1, 1)
FIRST_DATE = datetime.date(1998, 1, 1)
N_DAYS = 1826                       # 1998-01-01 .. 2002-12-31
FIRST_SK = 2450815                  # spec's julian-ish base for 1998-01-01

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = ["accent", "archery", "arts", "athletic", "audio", "baseball",
           "basketball", "bathroom", "bedding", "birdal", "blinds",
           "camcorders", "camping", "classical", "computers", "country"]
BRAND_BASES = ["amalg", "edu pack", "exporti", "importo", "scholar",
               "brand", "corp", "maxi", "univ", "nameless"]
COLORS_DS = ["aquamarine", "azure", "beige", "black", "blue", "brown",
             "burlywood", "chartreuse", "chiffon", "coral", "cornflower",
             "cream", "cyan", "dark", "deep", "dim", "dodger", "drab",
             "firebrick", "floral", "forest", "frosted", "gainsboro",
             "ghost", "goldenrod", "green", "grey", "honeydew", "hot",
             "indian", "ivory", "khaki", "lace", "lavender"]
SIZES = ["N/A", "economy", "extra large", "large", "medium", "petite",
         "small"]
UNITS = sorted(["Bunch", "Bundle", "Box", "Carton", "Case", "Cup",
                "Dozen", "Each", "Gram", "Gross", "Lb", "N/A", "Ounce",
                "Oz", "Pallet", "Pound", "Tbl", "Ton", "Tsp",
                "Unknown"])
GENDERS = ["F", "M"]
MARITAL = ["D", "M", "S", "U", "W"]
EDUCATION = ["2 yr Degree", "4 yr Degree", "Advanced Degree", "College",
             "Primary", "Secondary", "Unknown"]
CREDIT = ["Good", "High Risk", "Low Risk", "Unknown"]
BUY_POTENTIAL = sorted(["0-500", "1001-5000", "501-1000", ">10000",
                        "5001-10000", "Unknown"])
STATES = ["AL", "CA", "GA", "IL", "KS", "KY", "LA", "MI", "MN", "MO",
          "NC", "NE", "NY", "OH", "OK", "SD", "TN", "TX", "VA", "WA"]
COUNTIES = ["Barrow County", "Bronx County", "Daviess County",
            "Fairfield County", "Franklin Parish", "Luce County",
            "Mobile County", "Oglethorpe County", "Richland County",
            "Walker County", "Williamson County", "Ziebach County"]
CITIES = ["Antioch", "Bethel", "Centerville", "Clinton", "Edgewood",
          "Fairview", "Five Points", "Friendship", "Georgetown",
          "Glendale", "Greenfield", "Liberty", "Midway", "Mount Olive",
          "Mount Zion", "Oak Grove", "Oak Ridge", "Oakland", "Pleasant "
          "Grove", "Pleasant Hill", "Riverside", "Salem", "Springdale",
          "Springfield", "Sulphur Springs", "Union", "Unionville",
          "Walnut Grove", "Wildwood", "Woodland", "Woodville"]
FIRST_NAMES = sorted(["James", "John", "Robert", "Michael", "William",
                      "David", "Mary", "Patricia", "Linda", "Barbara",
                      "Elizabeth", "Jennifer", "Maria", "Susan",
                      "Margaret", "Dorothy"])
LAST_NAMES = sorted(["Smith", "Johnson", "Williams", "Jones", "Brown",
                     "Davis", "Miller", "Wilson", "Moore", "Taylor",
                     "Anderson", "Thomas", "Jackson", "White", "Harris",
                     "Martin"])
WEEKDAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
DAY_NAMES = sorted(WEEKDAYS)
REASONS = ["Did not fit", "Did not like the color", "Did not like the "
           "model", "Found a better price", "Gift exchange", "Lost my job",
           "No service location", "Not working any more", "Package was "
           "damaged", "Parts missing", "Stopped working", "unknown"]
YN = ["N", "Y"]

PRIMARY_KEYS = {
    "date_dim": ("d_date_sk",),
    "time_dim": ("t_time_sk",),
    "item": ("i_item_sk",),
    "customer": ("c_customer_sk",),
    "customer_address": ("ca_address_sk",),
    "customer_demographics": ("cd_demo_sk",),
    "household_demographics": ("hd_demo_sk",),
    "store": ("s_store_sk",),
    "promotion": ("p_promo_sk",),
    "warehouse": ("w_warehouse_sk",),
    "reason": ("r_reason_sk",),
    "web_site": ("web_site_sk",),
    "call_center": ("cc_call_center_sk",),
    "catalog_page": ("cp_catalog_page_sk",),
    "web_page": ("wp_web_page_sk",),
    "income_band": ("ib_income_band_sk",),
    "ship_mode": ("sm_ship_mode_sk",),
    "store_sales": ("ss_item_sk", "ss_ticket_number"),
    "store_returns": ("sr_item_sk", "sr_ticket_number"),
    "catalog_sales": ("cs_item_sk", "cs_order_number"),
    "catalog_returns": ("cr_item_sk", "cr_order_number"),
    "web_sales": ("ws_item_sk", "ws_order_number"),
    "web_returns": ("wr_item_sk", "wr_order_number"),
    "inventory": ("inv_date_sk", "inv_item_sk", "inv_warehouse_sk"),
}

SHIP_MODE_TYPES = ["EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT",
                   "REGULAR", "TWO DAY"]
CARRIERS = sorted(["AIRBORNE", "ALLIANCE", "BARIAN", "BOXBUNDLES", "DHL",
                   "FEDEX", "GERMA", "GREAT EASTERN", "HARMSTORF", "LATVIAN",
                   "MSC", "ORIENTAL", "PRIVATECARRIER", "RUPEKSA", "TBS",
                   "UPS", "USPS", "ZHOU", "ZOUROS", "DIAMOND"])
SALUTATIONS = sorted(["Dr.", "Miss", "Mr.", "Mrs.", "Ms.", "Sir"])
COUNTRIES = sorted(["UNITED STATES", "CANADA", "MEXICO", "GERMANY",
                    "FRANCE", "JAPAN", "BRAZIL", "INDIA", "CHINA",
                    "AUSTRALIA", "ITALY", "SPAIN", "NIGERIA", "KENYA",
                    "EGYPT", "PERU"])


def _pick(rng, pool: List[str], n: int) -> np.ndarray:
    return rng.integers(0, len(pool), n).astype(np.int32)


def _id_strings(prefix: str, keys: np.ndarray):
    strings = [f"{prefix}{int(k):016d}" for k in keys]
    return np.arange(len(strings), dtype=np.int32), list(strings)


def generate(scale: float, seed: int = 19980101) -> Dict[str, TableData]:
    """scale 0.01 ('tiny'): ~120k store_sales rows; row counts scale
    linearly for facts, slower for dimensions (as in dsdgen)."""
    rng = np.random.default_rng(seed)
    out: Dict[str, TableData] = {}

    def table(name, fields, columns, valids=None):
        pks = PRIMARY_KEYS.get(name, ())
        out[name] = TableData(name, Schema(tuple(fields)), columns,
                              primary_key=pks, valids=valids)

    # ---- date_dim -------------------------------------------------------
    n_dates = N_DAYS
    d_sk = FIRST_SK + np.arange(n_dates, dtype=np.int64)
    first_days = (FIRST_DATE - EPOCH).days
    d_date = first_days + np.arange(n_dates, dtype=np.int32)
    dates = [FIRST_DATE + datetime.timedelta(days=int(i))
             for i in range(n_dates)]
    d_year = np.array([d.year for d in dates], dtype=np.int32)
    d_moy = np.array([d.month for d in dates], dtype=np.int32)
    d_dom = np.array([d.day for d in dates], dtype=np.int32)
    d_qoy = (d_moy - 1) // 3 + 1
    d_dow = np.array([(d.weekday() + 1) % 7 for d in dates], dtype=np.int32)
    d_day_name = _codes_for([WEEKDAYS[int(w)] for w in d_dow],
                            DAY_NAMES)
    # spec-like sequences: d_week_seq continuous over weeks, d_month_seq
    # over months (q2/q59's 53-week self-joins, q6/q54's month windows)
    d_week_seq = ((d_date - int(d_date[0]) + int(d_dow[0])) // 7 +
                  5270).astype(np.int32)
    d_month_seq = ((d_year - 1998) * 12 + d_moy - 1 + 1176).astype(np.int32)
    table("date_dim",
          [Field("d_date_sk", BIGINT), Field("d_date", DATE),
           Field("d_year", INTEGER), Field("d_moy", INTEGER),
           Field("d_dom", INTEGER), Field("d_qoy", INTEGER),
           Field("d_dow", INTEGER), _dict_field("d_day_name", DAY_NAMES),
           Field("d_week_seq", INTEGER), Field("d_month_seq", INTEGER)],
          [d_sk, d_date, d_year, d_moy, d_dom, d_qoy, d_dow, d_day_name,
           d_week_seq, d_month_seq])

    # ---- time_dim -------------------------------------------------------
    n_times = 86400 // 60            # per-minute grain (spec is per-second)
    t_sk = np.arange(n_times, dtype=np.int64)
    t_hour = (t_sk // 60).astype(np.int32)
    t_minute = (t_sk % 60).astype(np.int32)
    table("time_dim",
          [Field("t_time_sk", BIGINT), Field("t_hour", INTEGER),
           Field("t_minute", INTEGER)],
          [t_sk, t_hour, t_minute])

    # ---- item -----------------------------------------------------------
    n_item = max(200, int(18000 * min(scale, 1.0) ** 0.5))
    i_sk = 1 + np.arange(n_item, dtype=np.int64)
    _, i_id_pool = _id_strings("AAAAAAAA", i_sk)
    i_id_codes = np.arange(n_item, dtype=np.int32)
    i_category_id = _pick(rng, CATEGORIES, n_item) + 1
    i_class_id = _pick(rng, CLASSES, n_item) + 1
    i_manufact_id = rng.integers(1, 1000, n_item).astype(np.int64)
    i_brand_id = (i_category_id.astype(np.int64) * 1000000 +
                  rng.integers(1, 10, n_item) * 1000 +
                  rng.integers(1, 100, n_item))
    brand_strings = [f"{BRAND_BASES[int(b) % 10]} #{int(b) % 1000}"
                     for b in i_brand_id]
    brand_pool = sorted(set(brand_strings))
    manufact_strings = [f"able{int(m):04d}" for m in i_manufact_id]
    manufact_pool = sorted(set(manufact_strings))
    i_current_price = rng.integers(10, 9900, n_item).astype(np.int64)
    i_manager_id = rng.integers(1, 101, n_item).astype(np.int64)
    i_wholesale_cost = rng.integers(5, 7000, n_item).astype(np.int64)
    # bounded pools for desc/product_name (dsdgen text, pool-capped like
    # the tpch comment columns)
    desc_pool = sorted({f"{COLORS_DS[a]} {COLORS_DS[b]} {CLASSES[c]}"
                        for a in range(len(COLORS_DS))
                        for b in range(0, len(COLORS_DS), 5)
                        for c in range(0, len(CLASSES), 3)})
    i_desc = rng.integers(0, len(desc_pool), n_item).astype(np.int32)
    prod_pool = sorted({f"{BRAND_BASES[a]}{BRAND_BASES[b]}"
                        for a in range(10) for b in range(10)})
    i_prod = rng.integers(0, len(prod_pool), n_item).astype(np.int32)
    table("item",
          [Field("i_item_sk", BIGINT),
           Field("i_item_id", VARCHAR, dictionary=tuple(i_id_pool)),
           _dict_field("i_category", CATEGORIES),
           Field("i_category_id", INTEGER),
           _dict_field("i_class", CLASSES), Field("i_class_id", INTEGER),
           Field("i_brand_id", BIGINT),
           Field("i_brand", VARCHAR, dictionary=tuple(brand_pool)),
           Field("i_manufact_id", BIGINT),
           Field("i_manufact", VARCHAR, dictionary=tuple(manufact_pool)),
           Field("i_current_price", D72),
           _dict_field("i_color", COLORS_DS), _dict_field("i_size", SIZES),
           _dict_field("i_units", UNITS), Field("i_manager_id", BIGINT),
           Field("i_wholesale_cost", D72),
           _dict_field("i_item_desc", desc_pool),
           _dict_field("i_product_name", prod_pool)],
          [i_sk, i_id_codes, i_category_id - 1, i_category_id,
           i_class_id - 1, i_class_id, i_brand_id,
           _codes_for(brand_strings, brand_pool), i_manufact_id,
           _codes_for(manufact_strings, manufact_pool), i_current_price,
           _pick(rng, COLORS_DS, n_item), _pick(rng, SIZES, n_item),
           _pick(rng, UNITS, n_item), i_manager_id,
           i_wholesale_cost, i_desc, i_prod])

    # ---- customer_demographics (cross product, spec: 1,920,800 rows;
    #      shrunk grid with same fields) --------------------------------
    grid = [(g, m, e, p, c, d1, d2, d3)
            for g in range(2) for m in range(5) for e in range(7)
            for p in (500, 1000, 5000, 10000) for c in range(4)
            for d1 in range(0, 4) for d2 in range(0, 2)
            for d3 in range(0, 2)]
    n_cd = len(grid)
    ga = np.array([g[0] for g in grid], dtype=np.int32)
    ma = np.array([g[1] for g in grid], dtype=np.int32)
    ea = np.array([g[2] for g in grid], dtype=np.int32)
    pa = np.array([g[3] for g in grid], dtype=np.int64)
    ca = np.array([g[4] for g in grid], dtype=np.int32)
    d1a = np.array([g[5] for g in grid], dtype=np.int64)
    d2a = np.array([g[6] for g in grid], dtype=np.int64)
    d3a = np.array([g[7] for g in grid], dtype=np.int64)
    table("customer_demographics",
          [Field("cd_demo_sk", BIGINT), _dict_field("cd_gender", GENDERS),
           _dict_field("cd_marital_status", MARITAL),
           _dict_field("cd_education_status", EDUCATION),
           Field("cd_purchase_estimate", BIGINT),
           _dict_field("cd_credit_rating", CREDIT),
           Field("cd_dep_count", BIGINT),
           Field("cd_dep_employed_count", BIGINT),
           Field("cd_dep_college_count", BIGINT)],
          [1 + np.arange(n_cd, dtype=np.int64), ga, ma, ea, pa, ca,
           d1a, d2a, d3a])

    # ---- household_demographics ----------------------------------------
    n_hd = 7200
    hd_sk = 1 + np.arange(n_hd, dtype=np.int64)
    table("household_demographics",
          [Field("hd_demo_sk", BIGINT), Field("hd_income_band_sk", BIGINT),
           _dict_field("hd_buy_potential", BUY_POTENTIAL),
           Field("hd_dep_count", BIGINT),
           Field("hd_vehicle_count", BIGINT)],
          [hd_sk, 1 + hd_sk % 20, _pick(rng, BUY_POTENTIAL, n_hd),
           (hd_sk % 10).astype(np.int64), (hd_sk % 5).astype(np.int64)])

    # ---- customer_address ----------------------------------------------
    n_ca = max(1000, int(50000 * min(scale, 1.0) ** 0.5))
    ca_sk = 1 + np.arange(n_ca, dtype=np.int64)
    _, ca_id_pool = _id_strings("AAAAAAAA", ca_sk)
    zips = 10000 + (rng.integers(0, 400, n_ca) * 171) % 90000
    zip_strings = [f"{int(z):05d}" for z in zips]
    zip_pool = sorted(set(zip_strings))
    table("customer_address",
          [Field("ca_address_sk", BIGINT),
           Field("ca_address_id", VARCHAR, dictionary=tuple(ca_id_pool)),
           _dict_field("ca_city", CITIES),
           _dict_field("ca_county", COUNTIES),
           _dict_field("ca_state", STATES),
           Field("ca_zip", VARCHAR, dictionary=tuple(zip_pool)),
           _dict_field("ca_country", ["United States"]),
           Field("ca_gmt_offset", decimal(5, 2))],
          [ca_sk, np.arange(n_ca, dtype=np.int32),
           _pick(rng, CITIES, n_ca), _pick(rng, COUNTIES, n_ca),
           _pick(rng, STATES, n_ca), _codes_for(zip_strings, zip_pool),
           np.zeros(n_ca, dtype=np.int32),
           -rng.integers(500, 801, n_ca).astype(np.int64)])

    # ---- customer -------------------------------------------------------
    n_cust = max(1000, int(100000 * min(scale, 1.0) ** 0.5))
    c_sk = 1 + np.arange(n_cust, dtype=np.int64)
    _, c_id_pool = _id_strings("AAAAAAAA", c_sk)
    table("customer",
          [Field("c_customer_sk", BIGINT),
           Field("c_customer_id", VARCHAR, dictionary=tuple(c_id_pool)),
           Field("c_current_cdemo_sk", BIGINT),
           Field("c_current_hdemo_sk", BIGINT),
           Field("c_current_addr_sk", BIGINT),
           _dict_field("c_first_name", FIRST_NAMES),
           _dict_field("c_last_name", LAST_NAMES),
           Field("c_birth_year", INTEGER),
           Field("c_birth_month", INTEGER),
           _dict_field("c_preferred_cust_flag", YN),
           _dict_field("c_salutation", SALUTATIONS),
           _dict_field("c_birth_country", COUNTRIES)],
          [c_sk, np.arange(n_cust, dtype=np.int32),
           rng.integers(1, n_cd + 1, n_cust).astype(np.int64),
           rng.integers(1, n_hd + 1, n_cust).astype(np.int64),
           rng.integers(1, n_ca + 1, n_cust).astype(np.int64),
           _pick(rng, FIRST_NAMES, n_cust), _pick(rng, LAST_NAMES, n_cust),
           rng.integers(1924, 1993, n_cust).astype(np.int32),
           rng.integers(1, 13, n_cust).astype(np.int32),
           _pick(rng, YN, n_cust), _pick(rng, SALUTATIONS, n_cust),
           _pick(rng, COUNTRIES, n_cust)])

    # ---- store ----------------------------------------------------------
    n_store = max(12, int(12 * max(scale, 0.01) ** 0.5 * 10))
    s_sk = 1 + np.arange(n_store, dtype=np.int64)
    _, s_id_pool = _id_strings("AAAAAAAA", s_sk)
    store_names = sorted(["ese", "ought", "able", "pri", "cally",
                          "ation", "eing", "bar", "anti", "cation"])
    table("store",
          [Field("s_store_sk", BIGINT),
           Field("s_store_id", VARCHAR, dictionary=tuple(s_id_pool)),
           _dict_field("s_store_name", store_names),
           Field("s_number_employees", INTEGER),
           Field("s_floor_space", INTEGER),
           _dict_field("s_city", CITIES), _dict_field("s_county", COUNTIES),
           _dict_field("s_state", STATES),
           Field("s_zip", VARCHAR, dictionary=tuple(zip_pool)),
           Field("s_market_id", INTEGER),
           Field("s_gmt_offset", decimal(5, 2))],
          [s_sk, np.arange(n_store, dtype=np.int32),
           _pick(rng, store_names, n_store),
           rng.integers(200, 300, n_store).astype(np.int32),
           rng.integers(5000000, 10000000, n_store).astype(np.int32),
           _pick(rng, CITIES, n_store), _pick(rng, COUNTIES, n_store),
           _pick(rng, STATES, n_store),
           rng.integers(0, len(zip_pool), n_store).astype(np.int32),
           rng.integers(1, 11, n_store).astype(np.int32),
           -rng.integers(500, 801, n_store).astype(np.int64)])

    # ---- promotion ------------------------------------------------------
    n_promo = max(300, int(300 * min(scale, 1.0) ** 0.5))
    p_sk = 1 + np.arange(n_promo, dtype=np.int64)
    _, p_id_pool = _id_strings("AAAAAAAA", p_sk)
    table("promotion",
          [Field("p_promo_sk", BIGINT),
           Field("p_promo_id", VARCHAR, dictionary=tuple(p_id_pool)),
           _dict_field("p_channel_dmail", YN),
           _dict_field("p_channel_email", YN),
           _dict_field("p_channel_tv", YN),
           _dict_field("p_channel_event", YN)],
          [p_sk, np.arange(n_promo, dtype=np.int32),
           _pick(rng, YN, n_promo), _pick(rng, YN, n_promo),
           _pick(rng, YN, n_promo), _pick(rng, YN, n_promo)])

    # ---- warehouse / reason / web_site ---------------------------------
    n_wh = 5
    wh_names = sorted(["Conventional childr", "Important issues liv",
                       "Doors canno", "Bad cards must make.",
                       "Rooms cook "])
    table("warehouse",
          [Field("w_warehouse_sk", BIGINT),
           _dict_field("w_warehouse_name", wh_names),
           Field("w_warehouse_sq_ft", INTEGER),
           _dict_field("w_state", STATES)],
          [1 + np.arange(n_wh, dtype=np.int64),
           np.arange(n_wh, dtype=np.int32),
           rng.integers(50000, 1000000, n_wh).astype(np.int32),
           _pick(rng, STATES, n_wh)])
    n_reason = len(REASONS)
    table("reason",
          [Field("r_reason_sk", BIGINT),
           _dict_field("r_reason_desc", REASONS)],
          [1 + np.arange(n_reason, dtype=np.int64),
           np.arange(n_reason, dtype=np.int32)])
    n_web = 30
    web_names = sorted(f"site_{i}" for i in range(n_web))
    table("web_site",
          [Field("web_site_sk", BIGINT),
           Field("web_name", VARCHAR, dictionary=tuple(web_names))],
          [1 + np.arange(n_web, dtype=np.int64),
           np.arange(n_web, dtype=np.int32)])

    # ---- call_center / catalog_page / web_page / income_band /
    #      ship_mode (the remaining spec dimensions) ---------------------
    n_cc = 6
    cc_names = sorted(["NY Metro", "Mid Atlantic", "North Midwest",
                       "Pacific Northwest", "California", "Hawaii/Alaska"])
    cc_mgrs = sorted(["Bob Belcher", "Felipe Perkins", "Mark Hightower",
                      "Larry Mccray", "Julius Durham", "Terry Askew"])
    table("call_center",
          [Field("cc_call_center_sk", BIGINT),
           _dict_field("cc_name", cc_names),
           _dict_field("cc_manager", cc_mgrs),
           _dict_field("cc_county", COUNTIES)],
          [1 + np.arange(n_cc, dtype=np.int64),
           np.arange(n_cc, dtype=np.int32),
           _pick(rng, cc_mgrs, n_cc), _pick(rng, COUNTIES, n_cc)])

    n_cp = max(100, int(11718 * min(scale, 1.0) ** 0.5))
    _, cp_id_pool = _id_strings("AAAAAAAA",
                                1 + np.arange(n_cp, dtype=np.int64))
    table("catalog_page",
          [Field("cp_catalog_page_sk", BIGINT),
           Field("cp_catalog_page_id", VARCHAR,
                 dictionary=tuple(cp_id_pool))],
          [1 + np.arange(n_cp, dtype=np.int64),
           np.arange(n_cp, dtype=np.int32)])

    n_wp = max(60, int(60 * min(scale, 1.0) ** 0.5))
    table("web_page",
          [Field("wp_web_page_sk", BIGINT),
           Field("wp_char_count", INTEGER)],
          [1 + np.arange(n_wp, dtype=np.int64),
           rng.integers(100, 8000, n_wp).astype(np.int32)])

    n_ib = 20
    ib_sk = 1 + np.arange(n_ib, dtype=np.int64)
    table("income_band",
          [Field("ib_income_band_sk", BIGINT),
           Field("ib_lower_bound", INTEGER),
           Field("ib_upper_bound", INTEGER)],
          [ib_sk, ((ib_sk - 1) * 10000).astype(np.int32),
           (ib_sk * 10000).astype(np.int32)])

    n_sm = 20
    sm_types = [SHIP_MODE_TYPES[i % len(SHIP_MODE_TYPES)]
                for i in range(n_sm)]
    sm_codes = sorted(["AIR", "SURFACE", "SEA"])
    table("ship_mode",
          [Field("sm_ship_mode_sk", BIGINT),
           _dict_field("sm_type", sorted(SHIP_MODE_TYPES)),
           _dict_field("sm_code", sm_codes),
           _dict_field("sm_carrier", CARRIERS)],
          [1 + np.arange(n_sm, dtype=np.int64),
           _codes_for(sm_types, sorted(SHIP_MODE_TYPES)),
           _pick(rng, sm_codes, n_sm),
           np.arange(n_sm, dtype=np.int32)])

    # ---- fact helper ----------------------------------------------------
    def fk(n, hi, null_frac=0.04):
        vals = rng.integers(1, hi + 1, n).astype(np.int64)
        valid = rng.random(n) >= null_frac
        return vals, valid

    def money(n, lo, hi):
        return rng.integers(lo, hi, n).astype(np.int64)

    # ---- store_sales ----------------------------------------------------
    n_ss = max(1000, int(12_000_000 * scale))   # linear in scale (dsdgen)
    n_tickets = max(1, n_ss // 12)
    ss_ticket = rng.integers(1, n_tickets + 1, n_ss).astype(np.int64)
    ss_sold_date = FIRST_SK + rng.integers(0, n_dates, n_ss).astype(
        np.int64)
    ss_date_v = rng.random(n_ss) >= 0.04
    ss_item = rng.integers(1, n_item + 1, n_ss).astype(np.int64)
    ss_cust, ss_cust_v = fk(n_ss, n_cust)
    ss_cdemo, ss_cdemo_v = fk(n_ss, n_cd)
    ss_hdemo, ss_hdemo_v = fk(n_ss, n_hd)
    ss_addr, ss_addr_v = fk(n_ss, n_ca)
    ss_store, ss_store_v = fk(n_ss, n_store)
    ss_promo, ss_promo_v = fk(n_ss, n_promo)
    ss_time = rng.integers(0, n_times, n_ss).astype(np.int64)
    ss_qty = rng.integers(1, 101, n_ss).astype(np.int64)
    ss_wholesale = money(n_ss, 100, 10000)
    ss_list = (ss_wholesale * (100 + rng.integers(0, 100, n_ss)) //
               100).astype(np.int64)
    ss_sales_price = (ss_list * rng.integers(20, 101, n_ss) //
                      100).astype(np.int64)
    ss_ext_sales = ss_sales_price * ss_qty
    ss_ext_list = ss_list * ss_qty
    ss_ext_wholesale = ss_wholesale * ss_qty
    ss_ext_discount = ss_ext_list - ss_ext_sales
    ss_ext_tax = ss_ext_sales * rng.integers(0, 9, n_ss) // 100
    ss_coupon = np.where(rng.random(n_ss) < 0.1,
                         ss_ext_sales * rng.integers(0, 50, n_ss) // 100,
                         0).astype(np.int64)
    ss_net_paid = ss_ext_sales - ss_coupon
    ss_net_paid_tax = ss_net_paid + ss_ext_tax
    ss_net_profit = ss_net_paid - ss_ext_wholesale
    table("store_sales",
          [Field("ss_sold_date_sk", BIGINT),
           Field("ss_sold_time_sk", BIGINT),
           Field("ss_item_sk", BIGINT), Field("ss_customer_sk", BIGINT),
           Field("ss_cdemo_sk", BIGINT), Field("ss_hdemo_sk", BIGINT),
           Field("ss_addr_sk", BIGINT), Field("ss_store_sk", BIGINT),
           Field("ss_promo_sk", BIGINT), Field("ss_ticket_number", BIGINT),
           Field("ss_quantity", BIGINT), Field("ss_wholesale_cost", D72),
           Field("ss_list_price", D72), Field("ss_sales_price", D72),
           Field("ss_ext_discount_amt", D72),
           Field("ss_ext_sales_price", D72),
           Field("ss_ext_wholesale_cost", D72),
           Field("ss_ext_list_price", D72), Field("ss_ext_tax", D72),
           Field("ss_coupon_amt", D72), Field("ss_net_paid", D72),
           Field("ss_net_paid_inc_tax", D72), Field("ss_net_profit", D72)],
          [ss_sold_date, ss_time, ss_item, ss_cust, ss_cdemo, ss_hdemo,
           ss_addr, ss_store, ss_promo, ss_ticket, ss_qty, ss_wholesale,
           ss_list, ss_sales_price, ss_ext_discount, ss_ext_sales,
           ss_ext_wholesale, ss_ext_list, ss_ext_tax, ss_coupon,
           ss_net_paid, ss_net_paid_tax, ss_net_profit],
          valids=[ss_date_v, None, None, ss_cust_v, ss_cdemo_v, ss_hdemo_v,
                  ss_addr_v, ss_store_v, ss_promo_v] + [None] * 14)

    # ---- store_returns (~10% of sales get returned) --------------------
    n_sr = n_ss // 10
    ridx = rng.choice(n_ss, n_sr, replace=False)
    sr_item = ss_item[ridx]
    sr_ticket = ss_ticket[ridx]
    sr_returned_date = np.minimum(ss_sold_date[ridx] +
                                  rng.integers(1, 60, n_sr),
                                  FIRST_SK + n_dates - 1).astype(np.int64)
    sr_cust = ss_cust[ridx]
    sr_cust_v = ss_cust_v[ridx]
    sr_store = ss_store[ridx]
    sr_store_v = ss_store_v[ridx]
    sr_reason, sr_reason_v = fk(n_sr, n_reason)
    sr_qty = np.maximum(1, ss_qty[ridx] // 2).astype(np.int64)
    sr_amt = ss_sales_price[ridx] * sr_qty
    sr_net_loss = sr_amt // 10 + money(n_sr, 50, 1000)
    table("store_returns",
          [Field("sr_returned_date_sk", BIGINT),
           Field("sr_item_sk", BIGINT), Field("sr_customer_sk", BIGINT),
           Field("sr_store_sk", BIGINT), Field("sr_reason_sk", BIGINT),
           Field("sr_ticket_number", BIGINT),
           Field("sr_return_quantity", BIGINT),
           Field("sr_return_amt", D72), Field("sr_net_loss", D72)],
          [sr_returned_date, sr_item, sr_cust, sr_store, sr_reason,
           sr_ticket, sr_qty, sr_amt, sr_net_loss],
          valids=[None, None, sr_cust_v, sr_store_v, sr_reason_v,
                  None, None, None, None])

    # ---- catalog_sales --------------------------------------------------
    n_cs = n_ss // 2
    cs_order = rng.integers(1, max(2, n_cs // 8), n_cs).astype(np.int64)
    cs_sold_date = FIRST_SK + rng.integers(0, n_dates, n_cs).astype(
        np.int64)
    cs_date_v = rng.random(n_cs) >= 0.04
    cs_ship_date = np.minimum(cs_sold_date + rng.integers(2, 90, n_cs),
                              FIRST_SK + n_dates - 1).astype(np.int64)
    cs_item = rng.integers(1, n_item + 1, n_cs).astype(np.int64)
    cs_cust, cs_cust_v = fk(n_cs, n_cust)
    cs_cdemo, cs_cdemo_v = fk(n_cs, n_cd)
    cs_hdemo, cs_hdemo_v = fk(n_cs, n_hd)
    cs_addr, cs_addr_v = fk(n_cs, n_ca)
    cs_wh, cs_wh_v = fk(n_cs, n_wh)
    cs_promo, cs_promo_v = fk(n_cs, n_promo)
    cs_qty = rng.integers(1, 101, n_cs).astype(np.int64)
    cs_wholesale = money(n_cs, 100, 10000)
    cs_list = (cs_wholesale * (100 + rng.integers(0, 100, n_cs)) //
               100).astype(np.int64)
    cs_sales_price = (cs_list * rng.integers(20, 101, n_cs) //
                      100).astype(np.int64)
    cs_ext_sales = cs_sales_price * cs_qty
    cs_ext_discount = (cs_list - cs_sales_price) * cs_qty
    cs_net_paid = cs_ext_sales
    cs_net_profit = cs_net_paid - cs_wholesale * cs_qty
    cs_cc, cs_cc_v = fk(n_cs, n_cc)
    cs_cp, cs_cp_v = fk(n_cs, n_cp)
    cs_sm, cs_sm_v = fk(n_cs, n_sm)
    cs_ship_cust, cs_ship_cust_v = fk(n_cs, n_cust)
    cs_ship_addr, cs_ship_addr_v = fk(n_cs, n_ca)
    cs_ext_list = cs_list * cs_qty
    cs_ext_wholesale = cs_wholesale * cs_qty
    cs_ext_tax = cs_ext_sales * rng.integers(0, 9, n_cs) // 100
    cs_coupon = np.where(rng.random(n_cs) < 0.1,
                         cs_ext_sales * rng.integers(0, 50, n_cs) // 100,
                         0).astype(np.int64)
    cs_ext_ship = money(n_cs, 0, 5000) * cs_qty // 10
    cs_net_paid_tax = cs_net_paid + cs_ext_tax
    table("catalog_sales",
          [Field("cs_sold_date_sk", BIGINT),
           Field("cs_ship_date_sk", BIGINT), Field("cs_item_sk", BIGINT),
           Field("cs_bill_customer_sk", BIGINT),
           Field("cs_bill_cdemo_sk", BIGINT),
           Field("cs_bill_hdemo_sk", BIGINT),
           Field("cs_bill_addr_sk", BIGINT),
           Field("cs_warehouse_sk", BIGINT), Field("cs_promo_sk", BIGINT),
           Field("cs_order_number", BIGINT), Field("cs_quantity", BIGINT),
           Field("cs_wholesale_cost", D72), Field("cs_list_price", D72),
           Field("cs_sales_price", D72), Field("cs_ext_discount_amt", D72),
           Field("cs_ext_sales_price", D72), Field("cs_net_paid", D72),
           Field("cs_net_profit", D72),
           Field("cs_call_center_sk", BIGINT),
           Field("cs_catalog_page_sk", BIGINT),
           Field("cs_ship_mode_sk", BIGINT),
           Field("cs_ship_customer_sk", BIGINT),
           Field("cs_ship_addr_sk", BIGINT),
           Field("cs_ext_list_price", D72),
           Field("cs_ext_wholesale_cost", D72),
           Field("cs_ext_tax", D72), Field("cs_coupon_amt", D72),
           Field("cs_ext_ship_cost", D72),
           Field("cs_net_paid_inc_tax", D72)],
          [cs_sold_date, cs_ship_date, cs_item, cs_cust, cs_cdemo,
           cs_hdemo, cs_addr, cs_wh, cs_promo, cs_order, cs_qty,
           cs_wholesale, cs_list, cs_sales_price, cs_ext_discount,
           cs_ext_sales, cs_net_paid, cs_net_profit,
           cs_cc, cs_cp, cs_sm, cs_ship_cust, cs_ship_addr, cs_ext_list,
           cs_ext_wholesale, cs_ext_tax, cs_coupon, cs_ext_ship,
           cs_net_paid_tax],
          valids=[cs_date_v, None, None, cs_cust_v, cs_cdemo_v, cs_hdemo_v,
                  cs_addr_v, cs_wh_v, cs_promo_v] + [None] * 9 +
                 [cs_cc_v, cs_cp_v, cs_sm_v, cs_ship_cust_v,
                  cs_ship_addr_v] + [None] * 6)

    # ---- catalog_returns (~10% of catalog sales) -----------------------
    n_cr = n_cs // 10
    cridx = rng.choice(n_cs, n_cr, replace=False)
    cr_returned_date = np.minimum(cs_sold_date[cridx] +
                                  rng.integers(1, 60, n_cr),
                                  FIRST_SK + n_dates - 1).astype(np.int64)
    cr_qty = np.maximum(1, cs_qty[cridx] // 2).astype(np.int64)
    cr_amt = cs_sales_price[cridx] * cr_qty
    cr_reason, cr_reason_v = fk(n_cr, n_reason)
    table("catalog_returns",
          [Field("cr_returned_date_sk", BIGINT),
           Field("cr_item_sk", BIGINT), Field("cr_order_number", BIGINT),
           Field("cr_returning_customer_sk", BIGINT),
           Field("cr_returning_addr_sk", BIGINT),
           Field("cr_call_center_sk", BIGINT),
           Field("cr_catalog_page_sk", BIGINT),
           Field("cr_warehouse_sk", BIGINT),
           Field("cr_reason_sk", BIGINT),
           Field("cr_return_quantity", BIGINT),
           Field("cr_return_amount", D72),
           Field("cr_return_amt_inc_tax", D72),
           Field("cr_refunded_cash", D72),
           Field("cr_net_loss", D72)],
          [cr_returned_date, cs_item[cridx], cs_order[cridx],
           cs_cust[cridx], cs_addr[cridx], cs_cc[cridx], cs_cp[cridx],
           cs_wh[cridx], cr_reason, cr_qty, cr_amt,
           cr_amt + cr_amt * 8 // 100,
           cr_amt * rng.integers(50, 101, n_cr) // 100,
           cr_amt // 10 + money(n_cr, 50, 1000)],
          valids=[None, None, None, cs_cust_v[cridx], cs_addr_v[cridx],
                  cs_cc_v[cridx], cs_cp_v[cridx], cs_wh_v[cridx],
                  cr_reason_v] + [None] * 5)

    # ---- web_sales ------------------------------------------------------
    n_ws = n_ss // 4
    ws_order = rng.integers(1, max(2, n_ws // 8), n_ws).astype(np.int64)
    ws_sold_date = FIRST_SK + rng.integers(0, n_dates, n_ws).astype(
        np.int64)
    ws_date_v = rng.random(n_ws) >= 0.04
    ws_item = rng.integers(1, n_item + 1, n_ws).astype(np.int64)
    ws_cust, ws_cust_v = fk(n_ws, n_cust)
    ws_addr, ws_addr_v = fk(n_ws, n_ca)
    ws_site, ws_site_v = fk(n_ws, n_web)
    ws_promo, ws_promo_v = fk(n_ws, n_promo)
    ws_qty = rng.integers(1, 101, n_ws).astype(np.int64)
    ws_wholesale = money(n_ws, 100, 10000)
    ws_list = (ws_wholesale * (100 + rng.integers(0, 100, n_ws)) //
               100).astype(np.int64)
    ws_sales_price = (ws_list * rng.integers(20, 101, n_ws) //
                      100).astype(np.int64)
    ws_ext_sales = ws_sales_price * ws_qty
    ws_net_paid = ws_ext_sales
    ws_net_profit = ws_net_paid - ws_wholesale * ws_qty
    ws_ship_date = np.minimum(ws_sold_date + rng.integers(2, 90, n_ws),
                              FIRST_SK + n_dates - 1).astype(np.int64)
    ws_time = rng.integers(0, n_times, n_ws).astype(np.int64)
    ws_wh, ws_wh_v = fk(n_ws, n_wh)
    ws_sm, ws_sm_v = fk(n_ws, n_sm)
    ws_wp, ws_wp_v = fk(n_ws, n_wp)
    ws_ship_cust, ws_ship_cust_v = fk(n_ws, n_cust)
    ws_ship_addr, ws_ship_addr_v = fk(n_ws, n_ca)
    ws_ship_hd, ws_ship_hd_v = fk(n_ws, n_hd)
    ws_ext_list = ws_list * ws_qty
    ws_ext_wholesale = ws_wholesale * ws_qty
    ws_ext_discount = ws_ext_list - ws_ext_sales
    ws_ext_tax = ws_ext_sales * rng.integers(0, 9, n_ws) // 100
    ws_coupon = np.where(rng.random(n_ws) < 0.1,
                         ws_ext_sales * rng.integers(0, 50, n_ws) // 100,
                         0).astype(np.int64)
    ws_ext_ship = money(n_ws, 0, 5000) * ws_qty // 10
    ws_net_paid_tax = ws_net_paid + ws_ext_tax
    table("web_sales",
          [Field("ws_sold_date_sk", BIGINT), Field("ws_item_sk", BIGINT),
           Field("ws_bill_customer_sk", BIGINT),
           Field("ws_bill_addr_sk", BIGINT),
           Field("ws_web_site_sk", BIGINT), Field("ws_promo_sk", BIGINT),
           Field("ws_order_number", BIGINT), Field("ws_quantity", BIGINT),
           Field("ws_sales_price", D72), Field("ws_ext_sales_price", D72),
           Field("ws_net_paid", D72), Field("ws_net_profit", D72),
           Field("ws_ship_date_sk", BIGINT),
           Field("ws_sold_time_sk", BIGINT),
           Field("ws_warehouse_sk", BIGINT),
           Field("ws_ship_mode_sk", BIGINT),
           Field("ws_web_page_sk", BIGINT),
           Field("ws_ship_customer_sk", BIGINT),
           Field("ws_ship_addr_sk", BIGINT),
           Field("ws_ship_hdemo_sk", BIGINT),
           Field("ws_wholesale_cost", D72), Field("ws_list_price", D72),
           Field("ws_ext_list_price", D72),
           Field("ws_ext_wholesale_cost", D72),
           Field("ws_ext_discount_amt", D72), Field("ws_ext_tax", D72),
           Field("ws_coupon_amt", D72), Field("ws_ext_ship_cost", D72),
           Field("ws_net_paid_inc_tax", D72)],
          [ws_sold_date, ws_item, ws_cust, ws_addr, ws_site, ws_promo,
           ws_order, ws_qty, ws_sales_price, ws_ext_sales, ws_net_paid,
           ws_net_profit,
           ws_ship_date, ws_time, ws_wh, ws_sm, ws_wp, ws_ship_cust,
           ws_ship_addr, ws_ship_hd, ws_wholesale, ws_list, ws_ext_list,
           ws_ext_wholesale, ws_ext_discount, ws_ext_tax, ws_coupon,
           ws_ext_ship, ws_net_paid_tax],
          valids=[ws_date_v, None, ws_cust_v, ws_addr_v, ws_site_v,
                  ws_promo_v] + [None] * 6 +
                 [None, None, ws_wh_v, ws_sm_v, ws_wp_v, ws_ship_cust_v,
                  ws_ship_addr_v, ws_ship_hd_v] + [None] * 9)

    # ---- web_returns (~10% of web sales) -------------------------------
    n_wr = n_ws // 10
    wridx = rng.choice(n_ws, n_wr, replace=False)
    wr_returned_date = np.minimum(ws_sold_date[wridx] +
                                  rng.integers(1, 60, n_wr),
                                  FIRST_SK + n_dates - 1).astype(np.int64)
    wr_qty = np.maximum(1, ws_qty[wridx] // 2).astype(np.int64)
    wr_amt = ws_sales_price[wridx] * wr_qty
    wr_reason, wr_reason_v = fk(n_wr, n_reason)
    table("web_returns",
          [Field("wr_returned_date_sk", BIGINT),
           Field("wr_item_sk", BIGINT), Field("wr_order_number", BIGINT),
           Field("wr_returning_customer_sk", BIGINT),
           Field("wr_returning_addr_sk", BIGINT),
           Field("wr_refunded_customer_sk", BIGINT),
           Field("wr_web_page_sk", BIGINT),
           Field("wr_reason_sk", BIGINT),
           Field("wr_return_quantity", BIGINT),
           Field("wr_return_amt", D72),
           Field("wr_refunded_cash", D72),
           Field("wr_net_loss", D72)],
          [wr_returned_date, ws_item[wridx], ws_order[wridx],
           ws_cust[wridx], ws_addr[wridx], ws_cust[wridx], ws_wp[wridx],
           wr_reason, wr_qty, wr_amt,
           wr_amt * rng.integers(50, 101, n_wr) // 100,
           wr_amt // 10 + money(n_wr, 50, 1000)],
          valids=[None, None, None, ws_cust_v[wridx], ws_addr_v[wridx],
                  ws_cust_v[wridx], ws_wp_v[wridx], wr_reason_v] +
                 [None] * 4)

    # ---- inventory ------------------------------------------------------
    # weekly grain: every ~7th date x item sample x warehouse
    inv_dates = d_sk[::7]
    n_inv_items = min(n_item, 400)
    inv_d, inv_i, inv_w = np.meshgrid(
        inv_dates, i_sk[:n_inv_items], 1 + np.arange(n_wh, dtype=np.int64),
        indexing="ij")
    inv_d = inv_d.ravel()
    inv_i = inv_i.ravel()
    inv_w = inv_w.ravel()
    inv_qty = rng.integers(0, 1000, inv_d.shape[0]).astype(np.int64)
    table("inventory",
          [Field("inv_date_sk", BIGINT), Field("inv_item_sk", BIGINT),
           Field("inv_warehouse_sk", BIGINT),
           Field("inv_quantity_on_hand", BIGINT)],
          [inv_d, inv_i, inv_w, inv_qty])

    return out
