"""TPC-DS connector.

Reference: plugin/trino-tpcds (TpcdsMetadata/TpcdsRecordSet over the
Teradata generators) — schemas tiny/sf1/... map to scale factors, tables
generated deterministically and cached per scale.
"""

from __future__ import annotations

import re
from typing import Dict, Optional

from ..tpch.datagen import TableData
from .datagen import PRIMARY_KEYS, generate

_SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0,
            "sf1000": 1000.0}

TABLE_NAMES = list(PRIMARY_KEYS)


class TpcdsConnector:
    name = "tpcds"

    def __init__(self):
        self._cache: Dict[float, Dict[str, TableData]] = {}

    @staticmethod
    def scale_for_schema(schema: str) -> Optional[float]:
        if schema in _SCHEMAS:
            return _SCHEMAS[schema]
        m = re.fullmatch(r"sf([0-9.]+)", schema)
        if m:
            return float(m.group(1))
        return None

    def schema_names(self):
        return list(_SCHEMAS)

    def table_names(self, schema: str):
        return list(TABLE_NAMES)

    DISK_CACHE_MIN_SCALE = 1.0     # see tpch/connector.py

    def get_table(self, schema: str, table: str) -> TableData:
        scale = self.scale_for_schema(schema)
        if scale is None:
            raise KeyError(f"tpcds schema {schema!r} not found")
        if table not in TABLE_NAMES:
            raise KeyError(f"tpcds table {table!r} not found")
        from ..diskcache import get_or_generate
        return get_or_generate(
            f"tpcds_sf{scale:g}", table, self._cache.setdefault(scale, {}),
            lambda: generate(scale), TableData,
            use_disk=scale >= self.DISK_CACHE_MIN_SCALE)

    def get_table_schema(self, schema: str, table: str):
        """Scale-independent schema without data generation (see tpch)."""
        return self.get_table("tiny", table).schema
