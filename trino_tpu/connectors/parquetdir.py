"""Parquet file connector.

Reference role: the parquet storage tier (lib/trino-parquet
reader/ParquetReader.java:103 feeding the hive-style connectors). A root
directory holds schemas as subdirectories and tables as `<name>.parquet`
files; columns map onto the engine's types:

- INT64 -> BIGINT, INT32 -> INTEGER, DOUBLE -> DOUBLE, BOOLEAN -> BOOLEAN
- BYTE_ARRAY (UTF8) -> VARCHAR, dictionary-encoded at load (strings never
  reach the device — the ingest policy shared with every connector)

`export_table` writes engine tables back out (TableWriter + the parquet
writer), which is also how round-trip tests and benchmark datasets are
produced in an environment with no external parquet tooling.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import Field, Schema
from ..formats.parquet import read_parquet_file, write_parquet
from ..types import BIGINT, BOOLEAN, DOUBLE, INTEGER, TypeKind, VARCHAR
from .dirtable import StagedWriteMixin
from .tpch.datagen import TableData


def _pool_encode(values, mask, key=None):
    """Shared dictionary-building: sorted unique valid values -> (int32
    codes, pool tuple). Used by the varchar and array branches."""
    pool = sorted({v for v, m in zip(values, mask) if m}, key=key)
    index = {v: i for i, v in enumerate(pool)}
    codes = np.fromiter((index.get(v, 0) for v in values),
                        dtype=np.int32, count=len(values))
    return codes, tuple(pool)


def load_parquet(path: str, name: str,
                 predicates: Optional[dict] = None) -> TableData:
    """Decode a parquet file into engine TableData. `predicates`
    (column name -> (lo, hi) physical bounds) skips row groups whose
    chunk statistics prove no match; the result then holds only the
    surviving groups' rows and records skipped/total row groups."""
    from ..types import DATE, decimal
    f = read_parquet_file(path, predicates)
    names, columns, valids, logicals = \
        f.names, f.columns, f.valids, f.logicals
    fields: List[Field] = []
    arrays: List[np.ndarray] = []
    out_valids: List[Optional[np.ndarray]] = []
    for cname, col, valid, logical in zip(names, columns, valids,
                                          logicals):
        if logical is not None and logical[0] == "list":
            # LIST leaves arrive as object arrays of per-row tuples;
            # arrays follow the engine's pool-id discipline
            from ..types import array_of
            mask = valid if valid is not None else \
                np.ones(len(col), dtype=np.bool_)

            def norm(t):
                if t is None:
                    return ()
                return tuple(None if x is None else
                             float(x) if isinstance(x, (float, np.floating))
                             else int(x) for x in t)
            normed = [norm(t) for t in col]
            elem_t = DOUBLE if any(
                isinstance(x, float) for t in normed for x in t) else BIGINT
            codes, pool = _pool_encode(
                normed, mask,
                key=lambda t: (len(t), tuple((x is None, x or 0)
                                             for x in t)))
            arrays.append(codes)
            fields.append(Field(cname, array_of(elem_t),
                                dictionary=pool))
            out_valids.append(valid)
            continue
        if col.dtype == object:              # BYTE_ARRAY -> dict varchar
            mask = valid if valid is not None else \
                np.ones(len(col), dtype=np.bool_)
            codes, pool = _pool_encode(col, mask)
            arrays.append(codes)
            fields.append(Field(cname, VARCHAR, dictionary=pool))
        elif logical is not None and logical[0] == "decimal":
            arrays.append(np.asarray(col, dtype=np.int64))
            fields.append(Field(cname, decimal(logical[1], logical[2])))
        elif logical is not None and logical[0] == "date":
            arrays.append(np.asarray(col, dtype=np.int32))
            fields.append(Field(cname, DATE))
        elif col.dtype == np.dtype("<i8"):
            arrays.append(np.asarray(col, dtype=np.int64))
            fields.append(Field(cname, BIGINT))
        elif col.dtype == np.dtype("<i4"):
            arrays.append(np.asarray(col, dtype=np.int32))
            fields.append(Field(cname, INTEGER))
        elif col.dtype == np.dtype("<f8"):
            arrays.append(np.asarray(col, dtype=np.float64))
            fields.append(Field(cname, DOUBLE))
        elif col.dtype == np.bool_:
            arrays.append(np.asarray(col))
            fields.append(Field(cname, BOOLEAN))
        else:
            raise ValueError(f"{name}.{cname}: unsupported parquet dtype "
                             f"{col.dtype}")
        out_valids.append(valid)
    if all(v is None for v in out_valids):
        out_valids = None
    data = TableData(name, Schema(tuple(fields)), arrays,
                     valids=out_valids)
    data.skipped_row_groups = f.skipped_row_groups
    data.total_row_groups = f.total_row_groups
    return data


def flatten_table(data: TableData, fmt: str):
    """Engine TableData -> (names, arrays, valids, logicals) for a
    columnar file writer: dictionary codes decode back to strings;
    DECIMAL/DATE carry logical annotations so a round trip reconstructs
    the exact engine types. Shared by the parquet and ORC exporters."""
    names, arrays, valids, logicals = [], [], [], []
    for i, f in enumerate(data.schema):
        names.append(f.name)
        col = np.asarray(data.columns[i])
        valid = None if data.valids is None else data.valids[i]
        logical = None
        if f.dtype.kind is TypeKind.ARRAY:
            # the flat writers cannot represent repeated leaves; silent
            # code-column output would corrupt a round trip
            raise ValueError(
                f"{data.name}.{f.name}: ARRAY columns cannot be "
                f"exported to {fmt} yet")
        if f.dtype.kind is TypeKind.VARCHAR:
            pool = np.array(f.dictionary, dtype=object)
            col = pool[col]
        elif f.dtype.kind is TypeKind.DECIMAL:
            col = col.astype(np.int64)
            logical = ("decimal", f.dtype.precision, f.dtype.scale)
        elif f.dtype.kind is TypeKind.DATE:
            col = col.astype(np.int32)
            logical = ("date",)
        arrays.append(col)
        valids.append(None if valid is None else np.asarray(valid))
        logicals.append(logical)
    return names, arrays, valids, logicals


def export_table(data: TableData, path: str) -> None:
    """Engine TableData -> parquet file."""
    write_parquet(path, *flatten_table(data, "parquet"))


class ParquetConnector(StagedWriteMixin):
    name = "parquet"
    ext = "parquet"
    fmt = "parquet"

    def __init__(self, root: str):
        self.root = root
        self._cache: Dict[Tuple[str, str], TableData] = {}
        # unclean-shutdown recovery: roll forward / sweep any staged
        # write state before the first scan can observe it
        self.sweep_on_startup()

    @staticmethod
    def _load(path: str, name: str,
              predicates: Optional[dict] = None) -> TableData:
        return load_parquet(path, name, predicates)

    def _schema_dir(self, schema: str) -> str:
        return os.path.join(self.root, schema)

    def schema_names(self):
        if not os.path.isdir(self.root):
            return []
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, d))
                      and not d.startswith("."))

    def table_names(self, schema: str):
        return self._list_tables(schema)

    def get_table(self, schema: str, table: str) -> TableData:
        key = (schema, table)
        if key not in self._cache:
            self._cache[key] = self._load_table(schema, table)
        return self._cache[key]

    def get_table_schema(self, schema: str, table: str) -> Schema:
        return self.get_table(schema, table).schema

    def get_table_pruned(self, schema: str, table: str,
                         ranges: dict) -> TableData:
        """Predicate-pruned decode: row groups whose chunk statistics
        cannot match `ranges` are never decompressed or decoded. The
        result is NOT cached as the table (its row set is
        predicate-specific); callers own caching under a
        predicate-aware key."""
        return self._load_table(schema, table, predicates=ranges)
