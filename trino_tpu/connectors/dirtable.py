"""Directory-table support shared by the orcdir and parquetdir connectors.

A table is either the legacy single file `<schema>/<table>.<ext>` or a
directory `<schema>/<table>/` of published parts (`part-NNNNN-<qtok>-rN.
<ext>`) written by the exactly-once commit protocol
(server/writeprotocol.py). Reads concatenate parts in sequence order,
merging VARCHAR dictionaries into one sorted pool (the engine-wide
invariant: code order == string order). Directory listings skip dotfiles
and write-protocol artifacts (`.staging/`, `*.journal`, temp names) so a
crashed write can never surface as a phantom table or partial data.

Writes — `create_table` / `insert` / `drop_table` — run the same staged
commit protocol locally: stage one attempt file, journal the intent,
publish by rename. A crash at any point leaves either the old table or
the new one, never a prefix.
"""

import os
import uuid
from typing import List, Optional

import numpy as np

from ..batch import Field, Schema
from ..server import writeprotocol as wp
from ..types import TypeKind
from .tpch.datagen import TableData


def is_artifact(name: str) -> bool:
    """Write-protocol / temp artifacts a directory scan must skip."""
    return (name.startswith(".") or name.endswith(".journal")
            or name.endswith(".tmp"))


def concat_table_data(name: str, parts: List[TableData]) -> TableData:
    """Concatenate decoded part tables into one TableData, merging
    VARCHAR pools into a single sorted dictionary."""
    if len(parts) == 1:
        p = parts[0]
        return TableData(name, p.schema, p.columns, valids=p.valids)
    base = parts[0].schema
    for p in parts[1:]:
        if tuple(f.name for f in p.schema) != tuple(f.name for f in base):
            raise ValueError(
                f"{name}: part schema mismatch "
                f"({[f.name for f in p.schema]} vs "
                f"{[f.name for f in base]})")
    fields: List[Field] = []
    columns: List[np.ndarray] = []
    valids: List[Optional[np.ndarray]] = []
    for i, f in enumerate(base):
        cols = [np.asarray(p.columns[i]) for p in parts]
        vs = [None if p.valids is None else p.valids[i] for p in parts]
        if f.dtype.kind is TypeKind.VARCHAR:
            pool = sorted({s for p in parts
                           for s in p.schema.fields[i].dictionary})
            index = {s: j for j, s in enumerate(pool)}
            remapped = []
            for p, c in zip(parts, cols):
                src = p.schema.fields[i].dictionary
                lut = np.array([index[s] for s in src] or [0],
                               dtype=np.int32)
                remapped.append(lut[c] if len(src) else
                                np.zeros(len(c), dtype=np.int32))
            columns.append(np.concatenate(remapped)
                           if remapped else np.empty(0, np.int32))
            fields.append(Field(f.name, f.dtype, dictionary=tuple(pool)))
        else:
            columns.append(np.concatenate(cols))
            fields.append(f)
        if all(v is None for v in vs):
            valids.append(None)
        else:
            valids.append(np.concatenate(
                [np.ones(len(c), dtype=np.bool_) if v is None
                 else np.asarray(v) for v, c in zip(vs, cols)]))
    if all(v is None for v in valids):
        valids = None
    return TableData(name, Schema(tuple(fields)), columns, valids=valids)


class StagedWriteMixin:
    """Write API + directory-table reads for file connectors. Hosts set
    `ext` ("orc"/"parquet"), `fmt`, and `_load(path, name, predicates)`."""

    supports_staged_writes = True

    def _table_dir(self, schema: str, table: str) -> str:
        return os.path.join(self._schema_dir(schema), table)

    def _table_file(self, schema: str, table: str) -> str:
        return os.path.join(self._schema_dir(schema),
                            f"{table}.{self.ext}")

    def _dir_parts(self, schema: str, table: str):
        return wp.list_parts(self._table_dir(schema, table))

    def table_exists(self, schema: str, table: str) -> bool:
        return (os.path.isfile(self._table_file(schema, table))
                or bool(self._dir_parts(schema, table)))

    def _list_tables(self, schema: str):
        d = self._schema_dir(schema)
        if not os.path.isdir(d):
            return []
        suffix = f".{self.ext}"
        names = set()
        for f in os.listdir(d):
            if is_artifact(f):
                continue
            p = os.path.join(d, f)
            if os.path.isfile(p) and f.endswith(suffix):
                names.add(f[:-len(suffix)])
            elif os.path.isdir(p) and wp.list_parts(p):
                names.add(f)
        return sorted(names)

    def _load_table(self, schema: str, table: str,
                    predicates: Optional[dict] = None) -> TableData:
        """Single file, directory of parts, or both (a legacy file that
        later received distributed INSERT parts), concatenated."""
        parts: List[TableData] = []
        fpath = self._table_file(schema, table)
        skipped = total = 0
        if os.path.isfile(fpath):
            parts.append(self._load(fpath, table, predicates))
        tdir = self._table_dir(schema, table)
        for pf in self._dir_parts(schema, table):
            parts.append(self._load(os.path.join(tdir, pf), table,
                                    predicates))
        if not parts:
            raise KeyError(f"{self.name} table {schema}.{table} not "
                           f"found ({fpath})")
        skipped_rg = total_rg = 0
        for p in parts:
            skipped += getattr(p, "skipped_stripes", 0)
            total += getattr(p, "total_stripes", 0)
            skipped_rg += getattr(p, "skipped_row_groups", 0)
            total_rg += getattr(p, "total_row_groups", 0)
        data = concat_table_data(table, parts)
        data.skipped_stripes = skipped
        data.total_stripes = total
        data.skipped_row_groups = skipped_rg
        data.total_row_groups = total_rg
        return data

    # ---- write API (staged commit, exactly-once even locally) --------

    def create_table(self, schema: str, name: str, data: TableData,
                     if_not_exists: bool = False) -> None:
        if self.table_exists(schema, name):
            if if_not_exists:
                return
            raise ValueError(f"table {schema}.{name} already exists")
        self._staged_write(schema, name, data)

    def insert(self, schema: str, name: str, arrays, valids,
               fields) -> int:
        existing = self.get_table(schema, name)
        merged_fields = []
        for cur, new in zip(existing.schema, fields):
            if cur.dtype.kind is not new.dtype.kind:
                raise ValueError(
                    f"insert into {schema}.{name}.{cur.name}: kind "
                    f"mismatch {cur.dtype.kind} vs {new.dtype.kind}")
            merged_fields.append(Field(cur.name, new.dtype,
                                       dictionary=new.dictionary))
        data = TableData(name, Schema(tuple(merged_fields)),
                         [np.asarray(a) for a in arrays],
                         valids=None if valids is None or
                         all(v is None for v in valids) else list(valids))
        self._staged_write(schema, name, data)
        return data.num_rows

    def drop_table(self, schema: str, name: str,
                   if_exists: bool = False) -> None:
        found = False
        fpath = self._table_file(schema, name)
        if os.path.isfile(fpath):
            os.unlink(fpath)
            found = True
        tdir = self._table_dir(schema, name)
        if os.path.isdir(tdir):
            import shutil
            shutil.rmtree(tdir, ignore_errors=True)
            found = True
        if not found and not if_exists:
            raise KeyError(f"table {schema}.{name} not found")
        self._cache.pop((schema, name), None)

    def _staged_write(self, schema: str, name: str, data: TableData,
                      query_id: Optional[str] = None, injector=None):
        tdir = self._table_dir(schema, name)
        os.makedirs(tdir, exist_ok=True)
        qid = query_id or f"local_{uuid.uuid4().hex[:12]}"
        m = wp.stage_table_data(tdir, data, qid, stage=0, partition=0,
                                attempt="a0", fmt=self.fmt,
                                injector=injector)
        stats = wp.commit(tdir, qid, [m], injector=injector)
        self._cache.pop((schema, name), None)
        return stats

    def sweep_on_startup(self) -> dict:
        return wp.sweep_root(self.root)
