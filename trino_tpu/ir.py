"""Engine-internal typed expression IR.

Reference: Trino lowers analyzed AST expressions to its own IR (sql/ir/, 29
files: Call, Constant, Comparison, Logical, ...) which the bytecode compilers
consume (sql/gen/ExpressionCompiler.java:38). Ours is the input to the JAX
tracer in ops/project.py — jit + XLA fusion replaces bytecode generation.

Every node is typed (``dtype``). The analyzer (planner/analyzer.py) produces
only well-typed trees; the compiler assumes them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from .types import (BIGINT, BOOLEAN, DATE, DOUBLE, DataType, TypeKind,
                    common_super_type, decimal)


class Expr:
    dtype: DataType


@dataclass(frozen=True)
class ColumnRef(Expr):
    index: int          # position in the input batch
    dtype: DataType
    name: str = ""      # for debugging / explain


@dataclass(frozen=True)
class Literal(Expr):
    value: object       # python int/float/bool/str/None; DECIMAL as scaled int
    dtype: DataType


@dataclass(frozen=True)
class Arith(Expr):
    """+ - * / following Trino's decimal scale rules
    (spi/type/DecimalOperators semantics for short decimals):
    add/sub -> max scale; mul -> s1+s2; div -> lowered to DOUBLE."""
    op: str             # '+', '-', '*', '/'
    left: Expr
    right: Expr
    dtype: DataType


@dataclass(frozen=True)
class Negate(Expr):
    arg: Expr
    dtype: DataType


@dataclass(frozen=True)
class Compare(Expr):
    op: str             # '=', '<>', '<', '<=', '>', '>='
    left: Expr
    right: Expr
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class Logical(Expr):
    """AND/OR with Kleene three-valued logic (Trino sql/ir/Logical.java)."""
    op: str             # 'and', 'or'
    args: tuple         # tuple[Expr, ...]
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class Not(Expr):
    arg: Expr
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr
    negated: bool = False
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class InList(Expr):
    arg: Expr
    values: tuple       # tuple[Literal, ...] coerced to arg's physical rep
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class Between(Expr):
    arg: Expr
    low: Expr
    high: Expr
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class Case(Expr):
    """Searched CASE. whens = ((cond, value), ...)."""
    whens: tuple
    default: Optional[Expr]
    dtype: DataType


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    dtype: DataType


@dataclass(frozen=True)
class DictPredicate(Expr):
    """Boolean predicate over a dictionary-encoded VARCHAR column, evaluated
    host-side over the string pool into a code->bool lookup table at plan
    time (LIKE, =, IN on strings). Device work is a single gather.

    This is the TPU answer to Trino's LikeMatcher DFA (likematcher/) and
    dictionary-aware processing in PageProcessor (SURVEY.md §7 strings)."""
    arg: Expr           # must be a VARCHAR ColumnRef
    lut: tuple          # tuple[bool, ...], len == dictionary size
    dtype: DataType = BOOLEAN


@dataclass(frozen=True)
class ScalarSubqueryRef(Expr):
    """Uncorrelated scalar subquery: holds the planned subplan. The executor
    runs it once, extracts the single value, and substitutes a Literal
    before tracing (Trino: uncorrelated subqueries execute as independent
    stages feeding a semi-join/filter; here they fold to a constant)."""
    plan: object        # L.OutputNode (opaque to avoid import cycle)
    dtype: DataType

    def __hash__(self):
        return id(self.plan)

    def __eq__(self, other):
        return self is other


@dataclass(frozen=True)
class DerivedDict(Expr):
    """VARCHAR expression computed by transforming the string pool
    host-side (e.g. substring over every pool entry) and remapping codes
    through `lut` into a deduplicated `pool`. Device work is one gather;
    canonical codes make GROUP BY / joins on the derived value correct
    even when source strings collide after the transform
    (SURVEY.md §7 strings policy)."""
    arg: Expr           # VARCHAR ColumnRef (or nested DerivedDict)
    lut: tuple          # old code -> new code (int), len == source pool
    pool: tuple         # deduplicated transformed pool (new code -> str)
    dtype: DataType     # VARCHAR
    null_code: Optional[int] = None   # coalesce: NULL rows take this
    #                                   code and become valid


@dataclass(frozen=True, eq=False)
class InSubqueryRef(Expr):
    """x IN (uncorrelated subquery) in a non-conjunct position (inside OR,
    select items). The executor folds it to InList over the executed
    subquery's values, with Kleene NULL injection when the subquery
    contains NULLs (x IN S is NULL when unmatched and S has NULL).
    Top-level conjuncts never reach this node — they decorrelate to
    semi/anti joins first. Hashes by identity (carries a plan)."""
    arg: "Expr"
    plan: object                 # logical plan of the subquery
    arg_field: object            # Optional[Field] — probe dictionary
    sub_field: object            # Optional[Field] — subquery dictionary

    @property
    def dtype(self):
        from .types import BOOLEAN
        return BOOLEAN

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


@dataclass(frozen=True)
class ScalarFunc(Expr):
    """Generic elementwise scalar function (abs/round/mod/coalesce/...).

    The engine analog of Trino's operator/scalar/ built-ins resolved via
    InternalFunctionBundle — evaluated branch-free in ops/project.py."""
    name: str
    args: tuple                  # tuple[Expr, ...]
    dtype: DataType
    params: tuple = ()           # static extras (e.g. round digits)


@dataclass(frozen=True)
class DictValueMap(Expr):
    """Map dictionary codes to precomputed host values (e.g. length(col)):
    one device gather through a per-code LUT."""
    arg: Expr                    # varchar codes
    values: tuple                # per-code value
    dtype: DataType


@dataclass(frozen=True)
class ArrayConst(Expr):
    """ARRAY[...] of constants: device sees pool id 0, the single-entry
    element pool rides in the expression (the dictionary discipline,
    types.py ARRAY)."""
    pool: tuple                  # ((elem, elem, ...),)
    dtype: DataType


@dataclass(frozen=True)
class DecimalAvg(Expr):
    """Exact decimal AVG finalizer: round-half-away-from-zero of
    sum/count at the argument's scale (Trino avg(decimal) semantics,
    computed with integer ops on device)."""
    sum: Expr
    count: Expr
    dtype: DataType


@dataclass(frozen=True)
class ExtractField(Expr):
    """EXTRACT(YEAR/MONTH/DAY FROM date_expr) — computes civil fields from
    epoch days on device."""
    part: str           # 'year', 'month', 'day'
    arg: Expr
    dtype: DataType = BIGINT


# --------------------------------------------------------------------------
# Constructors with type inference (used by the analyzer)
# --------------------------------------------------------------------------

def arith(op: str, left: Expr, right: Expr) -> Expr:
    lt, rt = left.dtype, right.dtype
    if op == '/':
        # Trino returns DECIMAL with complex scale rules; we lower division
        # to DOUBLE (documented deviation; exact where it matters — avg —
        # is handled by aggregate finalizers).
        if TypeKind.DOUBLE in (lt.kind, rt.kind) or \
           TypeKind.DECIMAL in (lt.kind, rt.kind):
            return Arith(op, left, right, DOUBLE)
        return Arith(op, left, right, common_super_type(lt, rt))
    if op == '*' and lt.kind is TypeKind.DECIMAL and rt.kind is TypeKind.DECIMAL:
        out = decimal(min(18, lt.precision + rt.precision), lt.scale + rt.scale)
        return Arith(op, left, right, out)
    if {lt.kind, rt.kind} == {TypeKind.DATE} and op == '-':
        return Arith(op, left, right, BIGINT)  # date difference in days
    return Arith(op, left, right, common_super_type(lt, rt))


def comparable(left: Expr, right: Expr) -> tuple:
    """Common comparison type for two sides (analyzer inserts Casts)."""
    return common_super_type(left.dtype, right.dtype)


def walk(expr: Expr):
    """Yield every node in the tree (pre-order)."""
    yield expr
    children = ()
    if isinstance(expr, Arith):
        children = (expr.left, expr.right)
    elif isinstance(expr, (Negate, Not, Cast, ExtractField, DictPredicate,
                           DerivedDict, DictValueMap)):
        children = (expr.arg,)
    elif isinstance(expr, ScalarFunc):
        children = expr.args
    elif isinstance(expr, InSubqueryRef):
        children = (expr.arg,)
    elif isinstance(expr, IsNull):
        children = (expr.arg,)
    elif isinstance(expr, Compare):
        children = (expr.left, expr.right)
    elif isinstance(expr, Logical):
        children = expr.args
    elif isinstance(expr, InList):
        children = (expr.arg,)
    elif isinstance(expr, Between):
        children = (expr.arg, expr.low, expr.high)
    elif isinstance(expr, Case):
        children = tuple(c for w in expr.whens for c in w) + \
            ((expr.default,) if expr.default is not None else ())
    elif isinstance(expr, DecimalAvg):
        children = (expr.sum, expr.count)
    for c in children:
        yield from walk(c)


def referenced_columns(expr: Expr) -> set:
    return {n.index for n in walk(expr) if isinstance(n, ColumnRef)}


def remap_columns(expr: Expr, mapping) -> Expr:
    """Rebuild an expression with ColumnRef indices translated through
    `mapping` (used by the column-pruning optimizer pass)."""
    if isinstance(expr, ColumnRef):
        return ColumnRef(mapping[expr.index], expr.dtype, expr.name)
    if isinstance(expr, (Literal, ArrayConst)):
        return expr
    if isinstance(expr, Arith):
        return Arith(expr.op, remap_columns(expr.left, mapping),
                     remap_columns(expr.right, mapping), expr.dtype)
    if isinstance(expr, Negate):
        return Negate(remap_columns(expr.arg, mapping), expr.dtype)
    if isinstance(expr, Compare):
        return Compare(expr.op, remap_columns(expr.left, mapping),
                       remap_columns(expr.right, mapping))
    if isinstance(expr, Logical):
        return Logical(expr.op, tuple(remap_columns(a, mapping)
                                      for a in expr.args))
    if isinstance(expr, Not):
        return Not(remap_columns(expr.arg, mapping))
    if isinstance(expr, IsNull):
        return IsNull(remap_columns(expr.arg, mapping), expr.negated)
    if isinstance(expr, InList):
        return InList(remap_columns(expr.arg, mapping), expr.values)
    if isinstance(expr, Between):
        return Between(remap_columns(expr.arg, mapping),
                       remap_columns(expr.low, mapping),
                       remap_columns(expr.high, mapping))
    if isinstance(expr, Case):
        return Case(tuple((remap_columns(c, mapping),
                           remap_columns(v, mapping))
                          for c, v in expr.whens),
                    None if expr.default is None
                    else remap_columns(expr.default, mapping), expr.dtype)
    if isinstance(expr, Cast):
        return Cast(remap_columns(expr.arg, mapping), expr.dtype)
    if isinstance(expr, DictPredicate):
        return DictPredicate(remap_columns(expr.arg, mapping), expr.lut)
    if isinstance(expr, DecimalAvg):
        return DecimalAvg(remap_columns(expr.sum, mapping),
                          remap_columns(expr.count, mapping), expr.dtype)
    if isinstance(expr, ExtractField):
        return ExtractField(expr.part, remap_columns(expr.arg, mapping),
                            expr.dtype)
    if isinstance(expr, DerivedDict):
        return DerivedDict(remap_columns(expr.arg, mapping), expr.lut,
                           expr.pool, expr.dtype, expr.null_code)
    if isinstance(expr, ScalarFunc):
        return ScalarFunc(expr.name,
                          tuple(remap_columns(a, mapping)
                                for a in expr.args),
                          expr.dtype, expr.params)
    if isinstance(expr, DictValueMap):
        return DictValueMap(remap_columns(expr.arg, mapping), expr.values,
                            expr.dtype)
    if isinstance(expr, ScalarSubqueryRef):
        return expr          # no column refs into the enclosing batch
    if isinstance(expr, InSubqueryRef):
        return InSubqueryRef(remap_columns(expr.arg, mapping), expr.plan,
                             expr.arg_field, expr.sub_field)
    raise NotImplementedError(type(expr).__name__)


def transform(expr: Expr, fn) -> Expr:
    """Pre-order structural rewrite: fn(node) -> replacement or None (to
    recurse into children). Generic over all IR dataclasses."""
    import dataclasses
    r = fn(expr)
    if r is not None:
        return r
    if not dataclasses.is_dataclass(expr):
        return expr
    changes = {}
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        nv = _transform_value(v, fn)
        if nv is not v:
            changes[f.name] = nv
    return dataclasses.replace(expr, **changes) if changes else expr


def _transform_value(v, fn):
    if isinstance(v, Expr):
        return transform(v, fn)
    if isinstance(v, tuple):
        items = tuple(_transform_value(x, fn) for x in v)
        if any(a is not b for a, b in zip(items, v)):
            return items
    return v
