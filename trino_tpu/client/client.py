"""Python client for the statement protocol.

Reference: client/trino-client's StatementClientV1
(StatementClientV1.java:76) — POST /v1/statement, then follow `nextUri`
(advance:391) accumulating data pages until no nextUri remains; DELETE the
current uri to cancel.
"""

from __future__ import annotations

import http.client
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional
from urllib.error import HTTPError
from urllib.request import Request, urlopen


class QueryError(Exception):
    def __init__(self, message: str, error_name: str = ""):
        super().__init__(message)
        self.error_name = error_name


@dataclass
class ClientResult:
    query_id: str
    columns: List[str]
    rows: List[list]
    state: str
    elapsed_ms: int = 0
    # how many times this query's polling crossed to a different
    # coordinator address (0 on the happy path; >=1 when a failover
    # happened under the query without surfacing an error)
    failovers: int = 0


class Client:
    def __init__(self, uri, user: str = "anonymous",
                 poll_interval_s: float = 0.05, timeout_s: float = 300.0,
                 spooled: bool = False, password: Optional[str] = None,
                 traceparent: Optional[str] = None,
                 on_progress=None):
        # `uri` accepts a single address, a comma-separated list, or a
        # list/tuple — the failover address list. The first entry is
        # the preferred coordinator; nextUri polling rewrites hosts
        # across the list when one stops answering (the HA client's
        # multi-host JDBC-URL pattern).
        if isinstance(uri, str):
            uris = [u for u in (p.strip() for p in uri.split(",")) if u]
        else:
            uris = [str(u) for u in uri]
        self.uris = [u.rstrip("/") for u in uris]
        self.uri = self.uris[0]
        self.user = user
        self.password = password   # X-Trino-Password credential
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        self.spooled = spooled     # opt into the spooled result protocol
        # W3C trace context: carried on every request (statement POST,
        # nextUri polls, spooled segment get/ack) so an enable_tracing
        # query's trace continues the CALLER's trace instead of rooting
        # a fresh one (utils/tracing.py parses it coordinator-side)
        self.traceparent = traceparent
        # live-progress hook: called with each polled page's `stats`
        # dict (state, progressRatio, stage, elapsedTimeMillis) — the
        # CLI's --progress line renders from this; None costs nothing
        self.on_progress = on_progress
        # cumulative coordinator-address switches (per-query delta is
        # reported on ClientResult.failovers)
        self.failovers = 0
        # the most recent nextUri — the Ctrl-C cancel target
        self._last_next_uri: Optional[str] = None
        from ..server.retrypolicy import RetryPolicy
        # the retry window must outlast a standby promotion (detector
        # misses + ledger replay + worker re-announce), not just a
        # connection blip — hence the deep attempt budget
        self.retry_policy = RetryPolicy(base_delay_s=0.05,
                                        max_delay_s=1.0, max_attempts=12,
                                        name="client-failover")

    def _request(self, method: str, url: str,
                 body: Optional[bytes] = None) -> dict:
        headers = {"X-Trino-User": self.user,
                   "Content-Type": "text/plain"}
        if self.password is not None:
            headers["X-Trino-Password"] = self.password
        if self.spooled:
            headers["X-Trino-Spooled"] = "true"
        if self.traceparent is not None:
            headers["traceparent"] = self.traceparent
        req = Request(url, data=body, method=method, headers=headers)
        with urlopen(req, timeout=30) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}

    # -- coordinator failover ----------------------------------------------

    @staticmethod
    def _rewrite(url: str, base: str) -> str:
        """Re-home a server-issued URI (nextUri, spooled segment) onto
        `base` — the statement routes are identical on every coordinator
        in the list, and a promoted standby resumes the query under the
        same id/token path the dead primary issued."""
        from urllib.parse import urlsplit, urlunsplit
        b = urlsplit(base)
        u = urlsplit(url)
        return urlunsplit((b.scheme, b.netloc, u.path, u.query,
                           u.fragment))

    def _next_coordinator(self, failed: str) -> None:
        """Rotate the polling target past `failed`; counts a failover
        only when the address actually changes."""
        if len(self.uris) < 2:
            return
        try:
            i = self.uris.index(failed)
        except ValueError:
            i = -1
        nxt = self.uris[(i + 1) % len(self.uris)]
        if nxt != failed:
            self.uri = nxt
            self.failovers += 1

    @staticmethod
    def _retryable_http(e: HTTPError) -> bool:
        """A coordinator that answers but cannot serve (a not-yet-
        promoted standby's 503 COORDINATOR_UNAVAILABLE, a proxy's 502)
        is a failover signal, not a query error."""
        return e.code in (502, 503)

    def _submit(self, sql: str) -> dict:
        """POST the statement, failing over across the address list
        ONLY on errors that guarantee nothing was admitted — a refused/
        unreachable connection, or an explicit COORDINATOR_UNAVAILABLE
        rejection. Once any coordinator has accepted the statement,
        recovery happens on the idempotent nextUri GETs instead (a
        re-POST would run the query twice)."""
        delays = self.retry_policy.delays()
        last: Optional[Exception] = None
        for _ in range(self.retry_policy.max_attempts):
            base = self.uri
            try:
                return self._request("POST", f"{base}/v1/statement",
                                     sql.encode())
            except HTTPError as e:
                if not self._retryable_http(e):
                    raise
                last = e
            except (OSError, http.client.HTTPException) as e:
                last = e
            self._next_coordinator(base)
            d = next(delays, None)
            if d is None:
                break
            time.sleep(d)
        raise QueryError(f"no coordinator accepted the statement: {last}",
                         "COORDINATOR_UNAVAILABLE")

    def execute(self, sql: str) -> ClientResult:
        """Submit and drain the nextUri chain to completion."""
        failovers_at_start = self.failovers
        doc = self._submit(sql)
        columns: List[str] = []
        rows: List[list] = []
        deadline = time.time() + self.timeout_s
        self._last_next_uri = None
        try:
            return self._drain(doc, columns, rows, deadline,
                               failovers_at_start)
        except KeyboardInterrupt:
            # Ctrl-C cancels the SERVER-side query before the client
            # exits — otherwise the interrupted query keeps burning
            # cluster slots until its own deadline fires
            nu = self._last_next_uri
            if nu:
                try:
                    self._request("DELETE", self._rewrite(nu, self.uri))
                except Exception:  # noqa: BLE001 — best-effort cancel
                    pass
            raise

    def _drain(self, doc: dict, columns: List[str], rows: List[list],
               deadline: float, failovers_at_start: int) -> ClientResult:
        while True:
            if "error" in doc:
                err = doc["error"]
                name = err.get("errorName", "")
                msg = err.get("message", "query failed")
                if name == "QUERY_EXCEEDED_RUN_TIME":
                    msg += (" — the query hit its query_max_run_time_s "
                            "budget: raise it (SET SESSION "
                            "query_max_run_time_s = N, or the CLI's "
                            "--timeout) or narrow the query")
                elif name in ("QUERY_QUEUE_FULL",
                              "QUERY_EXCEEDED_QUEUED_TIME"):
                    msg += (" — the cluster is overloaded and this "
                            "rejection is retryable: resubmit after a "
                            "backoff")
                raise QueryError(msg, name)
            if self.on_progress is not None:
                try:
                    self.on_progress(doc.get("stats") or {})
                except Exception:  # noqa: BLE001 — rendering never
                    pass           # fails the query
            if "columns" in doc and not columns:
                columns = [c["name"] for c in doc["columns"]]
            if "data" in doc:
                rows.extend(doc["data"])
            for seg in doc.get("segments", ()):
                # spooled protocol: fetch each segment, then acknowledge
                # (re-homed onto the current coordinator — spool storage
                # is shared, so a promoted standby serves the same keys)
                sdoc = self._request("GET",
                                     self._rewrite(seg["uri"], self.uri))
                rows.extend(sdoc["data"])
                self._request("DELETE",
                              self._rewrite(seg["uri"], self.uri))
            next_uri = doc.get("nextUri")
            self._last_next_uri = next_uri
            if next_uri is None:
                return ClientResult(
                    doc.get("id", ""), columns, rows,
                    doc.get("stats", {}).get("state", "FINISHED"),
                    doc.get("stats", {}).get("elapsedTimeMillis", 0),
                    failovers=self.failovers - failovers_at_start)
            if time.time() > deadline:
                # cancel the server-side query BEFORE raising — a bare
                # CLIENT_TIMEOUT used to leak the executing query (it
                # keeps burning cluster slots until ITS timeout); the
                # DELETE is best-effort so a dead coordinator can't mask
                # the timeout error itself
                try:
                    self._request("DELETE",
                                  self._rewrite(next_uri, self.uri))
                except Exception:     # noqa: BLE001 — best-effort cancel
                    pass
                raise QueryError("client timeout", "CLIENT_TIMEOUT")
            state = doc.get("stats", {}).get("state", "")
            if state in ("QUEUED", "PLANNING", "RUNNING", "STARTING"):
                time.sleep(self.poll_interval_s)
            doc = self._poll(next_uri)

    def _poll(self, next_uri: str) -> dict:
        """One nextUri advance, retried with backoff through the
        coordinator address list: a reset/refused/dropped connection or
        an explicit COORDINATOR_UNAVAILABLE answer rotates the target
        and re-issues the SAME uri against the next address (nextUri
        GETs are idempotent — the token pins the page, and a promoted
        standby resumes the query under the original id). The query
        survives its coordinator dying mid-poll without surfacing an
        error; HTTP status errors other than 502/503 are real answers
        and propagate (StatementClientV1.advance retries the same
        way)."""
        delays = self.retry_policy.delays()
        last: Optional[Exception] = None
        for _ in range(self.retry_policy.max_attempts):
            base = self.uri
            try:
                return self._request("GET",
                                     self._rewrite(next_uri, base))
            except HTTPError as e:
                if not self._retryable_http(e):
                    raise
                last = e
            except (OSError, http.client.HTTPException) as e:
                last = e
            self._next_coordinator(base)
            d = next(delays, None)
            if d is None:
                break
            time.sleep(max(d, self.poll_interval_s))
        raise last if isinstance(last, HTTPError) else \
            QueryError(f"lost every coordinator while polling: {last}",
                       "COORDINATOR_UNAVAILABLE")

    def query_info(self, query_id: str) -> dict:
        return self._request("GET", f"{self.uri}/v1/query/{query_id}")

    def list_queries(self) -> list:
        return self._request("GET", f"{self.uri}/v1/query")

    def nodes(self) -> list:
        return self._request("GET", f"{self.uri}/v1/node")

    def server_info(self) -> dict:
        return self._request("GET", f"{self.uri}/v1/info")
