"""Python client for the statement protocol.

Reference: client/trino-client's StatementClientV1
(StatementClientV1.java:76) — POST /v1/statement, then follow `nextUri`
(advance:391) accumulating data pages until no nextUri remains; DELETE the
current uri to cancel.
"""

from __future__ import annotations

import http.client
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional
from urllib.error import HTTPError
from urllib.request import Request, urlopen


class QueryError(Exception):
    def __init__(self, message: str, error_name: str = ""):
        super().__init__(message)
        self.error_name = error_name


@dataclass
class ClientResult:
    query_id: str
    columns: List[str]
    rows: List[list]
    state: str
    elapsed_ms: int = 0


class Client:
    def __init__(self, uri: str, user: str = "anonymous",
                 poll_interval_s: float = 0.05, timeout_s: float = 300.0,
                 spooled: bool = False, password: Optional[str] = None,
                 traceparent: Optional[str] = None):
        self.uri = uri.rstrip("/")
        self.user = user
        self.password = password   # X-Trino-Password credential
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        self.spooled = spooled     # opt into the spooled result protocol
        # W3C trace context: carried on every request (statement POST,
        # nextUri polls, spooled segment get/ack) so an enable_tracing
        # query's trace continues the CALLER's trace instead of rooting
        # a fresh one (utils/tracing.py parses it coordinator-side)
        self.traceparent = traceparent

    def _request(self, method: str, url: str,
                 body: Optional[bytes] = None) -> dict:
        headers = {"X-Trino-User": self.user,
                   "Content-Type": "text/plain"}
        if self.password is not None:
            headers["X-Trino-Password"] = self.password
        if self.spooled:
            headers["X-Trino-Spooled"] = "true"
        if self.traceparent is not None:
            headers["traceparent"] = self.traceparent
        req = Request(url, data=body, method=method, headers=headers)
        with urlopen(req, timeout=30) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}

    def execute(self, sql: str) -> ClientResult:
        """Submit and drain the nextUri chain to completion."""
        doc = self._request("POST", f"{self.uri}/v1/statement",
                            sql.encode())
        columns: List[str] = []
        rows: List[list] = []
        deadline = time.time() + self.timeout_s
        while True:
            if "error" in doc:
                err = doc["error"]
                raise QueryError(err.get("message", "query failed"),
                                 err.get("errorName", ""))
            if "columns" in doc and not columns:
                columns = [c["name"] for c in doc["columns"]]
            if "data" in doc:
                rows.extend(doc["data"])
            for seg in doc.get("segments", ()):
                # spooled protocol: fetch each segment, then acknowledge
                sdoc = self._request("GET", seg["uri"])
                rows.extend(sdoc["data"])
                self._request("DELETE", seg["uri"])
            next_uri = doc.get("nextUri")
            if next_uri is None:
                return ClientResult(
                    doc.get("id", ""), columns, rows,
                    doc.get("stats", {}).get("state", "FINISHED"),
                    doc.get("stats", {}).get("elapsedTimeMillis", 0))
            if time.time() > deadline:
                # cancel the server-side query BEFORE raising — a bare
                # CLIENT_TIMEOUT used to leak the executing query (it
                # keeps burning cluster slots until ITS timeout); the
                # DELETE is best-effort so a dead coordinator can't mask
                # the timeout error itself
                try:
                    self._request("DELETE", next_uri)
                except Exception:     # noqa: BLE001 — best-effort cancel
                    pass
                raise QueryError("client timeout", "CLIENT_TIMEOUT")
            state = doc.get("stats", {}).get("state", "")
            if state in ("QUEUED", "PLANNING", "RUNNING", "STARTING"):
                time.sleep(self.poll_interval_s)
            doc = self._poll(next_uri)

    def _poll(self, next_uri: str) -> dict:
        """One nextUri advance, tolerating a single transient connection
        failure: a reset/refused/dropped connection mid-poll is retried
        once after a short pause (nextUri GETs are idempotent — the
        token pins the page), so a coordinator hiccup doesn't abort a
        query that is still running fine. HTTP status errors are real
        answers and propagate (StatementClientV1.advance retries the
        same way)."""
        try:
            return self._request("GET", next_uri)
        except HTTPError:
            raise
        except (OSError, http.client.HTTPException):
            time.sleep(max(self.poll_interval_s, 0.05))
            return self._request("GET", next_uri)

    def query_info(self, query_id: str) -> dict:
        return self._request("GET", f"{self.uri}/v1/query/{query_id}")

    def list_queries(self) -> list:
        return self._request("GET", f"{self.uri}/v1/query")

    def nodes(self) -> list:
        return self._request("GET", f"{self.uri}/v1/node")

    def server_info(self) -> dict:
        return self._request("GET", f"{self.uri}/v1/info")
