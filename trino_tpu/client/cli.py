"""Interactive SQL CLI.

Reference: client/trino-cli (Console.java:87) — a line-oriented REPL that
submits statements and renders aligned result tables. `python -m
trino_tpu.client.cli [--server URI]`; with no --server it boots an
in-process engine (the StandaloneQueryRunner pattern) so the CLI works
without a running cluster.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


class ProgressLine:
    """Carriage-return progress line rendered from each polled page's
    stats: `[=====>      ]  52% RUNNING partitioned`. Monotonic — the
    shown ratio never moves backward even if a poll races a failover's
    progress re-derivation — and cleared before the result table so
    piped output never contains it."""

    WIDTH = 24

    def __init__(self, out=None):
        self.out = out if out is not None else sys.stderr
        self.ratio = 0.0
        self.visible = False

    def update(self, stats: dict) -> None:
        r = float(stats.get("progressRatio", 0.0) or 0.0)
        if stats.get("state") == "FINISHED":
            r = 1.0
        self.ratio = max(self.ratio, min(1.0, r))
        filled = int(self.ratio * self.WIDTH)
        bar = "=" * filled + (">" if filled < self.WIDTH else "")
        stage = stats.get("stage") or ""
        line = (f"[{bar:<{self.WIDTH}}] {100 * self.ratio:3.0f}% "
                f"{stats.get('state', '')} {stage}")
        self.out.write("\r" + line[:79].ljust(79))
        self.out.flush()
        self.visible = True

    def clear(self) -> None:
        if self.visible:
            self.out.write("\r" + " " * 79 + "\r")
            self.out.flush()
        self.visible = False
        self.ratio = 0.0


def progress_enabled(mode: str, out=None) -> bool:
    """Resolve --progress: 'always'/'never' are explicit; 'auto' turns
    the line on only for real interactive terminals — piped output and
    dumb terminals (no carriage-return rendering) stay clean."""
    if mode == "always":
        return True
    if mode == "never":
        return False
    out = out if out is not None else sys.stderr
    return bool(getattr(out, "isatty", lambda: False)()) and \
        os.environ.get("TERM", "") != "dumb"


def render_table(columns, rows, out=None) -> None:
    """Aligned ASCII table (the CLI's ALIGNED output format)."""
    out = out if out is not None else sys.stdout
    cells = [[("NULL" if v is None else str(v)) for v in r] for r in rows]
    widths = [len(c) for c in columns]
    for r in cells:
        for i, v in enumerate(r):
            widths[i] = max(widths[i], len(v))
    sep = "-+-".join("-" * w for w in widths)
    out.write(" | ".join(c.ljust(w) for c, w in zip(columns, widths))
              + "\n")
    out.write(sep + "\n")
    for r in cells:
        out.write(" | ".join(v.ljust(w) for v, w in zip(r, widths)) + "\n")
    out.write(f"({len(rows)} row{'s' if len(rows) != 1 else ''})\n")


class LocalBackend:
    """In-process engine (no server)."""

    def __init__(self, schema: str = "tiny",
                 timeout_s: float = 0.0):
        from ..exec.session import Session
        self.session = Session(default_schema=schema)
        if timeout_s > 0:
            # --timeout maps onto the engine's own deadline property so
            # local and remote modes bound queries the same way
            self.session.execute(
                f"SET SESSION query_max_run_time_s = {timeout_s}")

    def execute(self, sql: str):
        r = self.session.execute(sql)
        return r.column_names, r.rows


class RemoteBackend:
    def __init__(self, uri: str, user: str, progress: bool = False,
                 timeout_s: float = 0.0):
        from .client import Client
        self.progress_line = ProgressLine() if progress else None
        # --server accepts a comma-separated coordinator list; polling
        # fails over across it (client.py)
        self.client = Client(
            uri, user=user,
            on_progress=(self.progress_line.update
                         if self.progress_line is not None else None))
        self.last_failovers = 0
        if timeout_s > 0:
            # server-side deadline: the coordinator stamps it at
            # admission and enforces it end-to-end (workers included) —
            # strictly stronger than a client-side poll timeout
            self.client.execute(
                f"SET SESSION query_max_run_time_s = {timeout_s}")

    def execute(self, sql: str):
        try:
            r = self.client.execute(sql)
        finally:
            # the line must be gone before the table (or the error)
            # renders, success or not
            if self.progress_line is not None:
                self.progress_line.clear()
        self.last_failovers = r.failovers
        return r.columns, r.rows


def repl(backend, inp=sys.stdin, out=sys.stdout) -> None:
    buf = []
    prompt = "trino-tpu> "
    cont = "        -> "
    while True:
        out.write(prompt if not buf else cont)
        out.flush()
        line = inp.readline()
        if not line:
            break
        line = line.rstrip("\n")
        if not buf and line.strip().lower() in ("quit", "exit", "quit;",
                                                "exit;"):
            break
        if not line.strip():
            continue
        buf.append(line)
        if not line.rstrip().endswith(";"):
            continue
        sql = "\n".join(buf).rstrip().rstrip(";")
        buf = []
        t0 = time.monotonic()
        try:
            columns, rows = backend.execute(sql)
        except KeyboardInterrupt:
            # the client already sent the server-side DELETE before
            # re-raising (client.py); keep the REPL alive
            out.write("Query canceled.\n")
            continue
        except Exception as e:           # noqa: BLE001 — REPL boundary
            out.write(f"Query failed: {e}\n")
            continue
        render_table(columns, rows, out)
        summary = f"Elapsed: {time.monotonic() - t0:.2f}s"
        fo = getattr(backend, "last_failovers", 0)
        if fo:
            # the query crossed coordinators mid-flight and still
            # finished — worth telling the operator at the prompt
            summary += f"  Failovers: {fo}"
        out.write(summary + "\n\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu-cli")
    ap.add_argument("--server", help="coordinator URI (default: in-process)")
    ap.add_argument("--user", default="cli")
    ap.add_argument("--schema", default="tiny",
                    help="tpch schema for in-process mode")
    ap.add_argument("--execute", "-e", help="run one statement and exit")
    ap.add_argument("--progress", choices=("auto", "always", "never"),
                    default="auto",
                    help="live progress line while a remote query runs "
                         "(auto: only on interactive terminals)")
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="per-query run-time budget in seconds (maps to "
                         "SET SESSION query_max_run_time_s; the server "
                         "enforces it end-to-end)")
    args = ap.parse_args(argv)
    # local execution is synchronous — there is nothing to poll, so the
    # progress line only ever applies to --server mode
    backend = RemoteBackend(args.server, args.user,
                            progress=progress_enabled(args.progress),
                            timeout_s=args.timeout) \
        if args.server else LocalBackend(args.schema,
                                         timeout_s=args.timeout)
    if args.execute:
        try:
            columns, rows = backend.execute(args.execute.rstrip(";"))
        except KeyboardInterrupt:
            # client.py already DELETEd the server-side query
            sys.stderr.write("Query canceled.\n")
            return 130
        render_table(columns, rows)
        fo = getattr(backend, "last_failovers", 0)
        if fo:
            sys.stdout.write(f"Failovers: {fo}\n")
        return 0
    repl(backend)
    return 0


if __name__ == "__main__":
    sys.exit(main())
