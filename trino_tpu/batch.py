"""Columnar batch format — the Page/Block data model, TPU edition.

Reference: Trino's ``Page`` (spi/Page.java:31) is an immutable batch of
``Block`` columns with per-block null masks, plus dictionary and RLE wrappers
(spi/block/DictionaryBlock.java, RunLengthEncodedBlock.java).

XLA requires static shapes, so the single biggest divergence from the
reference (SURVEY.md §7 "hard parts" #1) is resolved here once:

- A :class:`Batch` has a fixed *capacity*; real rows are marked by a ``live``
  boolean mask. Filtering ANDs into ``live`` (zero data movement — Trino's
  ``SelectedPositions`` without the copy); compaction happens only at
  exchange/output boundaries via two-pass mask-then-gather.
- Every column carries a ``valid`` mask (SQL NULL). ``live`` and ``valid``
  are distinct: a live row may hold a NULL value.
- VARCHAR columns are int32 dictionary codes; string pools live host-side in
  the :class:`Schema` and never touch the device.

Batches are JAX pytrees, so they flow through ``jit``/``shard_map`` directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .types import DataType, TypeKind


# --------------------------------------------------------------------------
# Schema — host-side, hashable, holds dictionary pools
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    # String pool for VARCHAR columns (code -> string). Tuple for hashability.
    dictionary: Optional[tuple] = None


@dataclass(frozen=True)
class Schema:
    fields: tuple

    @staticmethod
    def of(*fields: Field) -> "Schema":
        return Schema(tuple(fields))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"no column {name!r} in {self.names}")

    @property
    def names(self):
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        return self.fields[self.index_of(name)]


# --------------------------------------------------------------------------
# Column / Batch pytrees
# --------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclass
class Column:
    """One column: flat typed array + validity mask (Trino Block)."""

    data: jax.Array   # [capacity], dtype per DataType.np_dtype
    valid: jax.Array  # [capacity] bool; False = SQL NULL

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


@jax.tree_util.register_dataclass
@dataclass
class Batch:
    """A fixed-capacity batch of columns (Trino Page).

    ``live[i]`` marks whether row i exists. All columns share capacity.
    """

    columns: tuple          # tuple[Column, ...]
    live: jax.Array         # [capacity] bool

    @property
    def capacity(self) -> int:
        return self.live.shape[0]

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> Column:
        return self.columns[i]

    def with_live(self, live: jax.Array) -> "Batch":
        return Batch(columns=self.columns, live=live)

    def select_columns(self, indices: Sequence[int]) -> "Batch":
        return Batch(columns=tuple(self.columns[i] for i in indices),
                     live=self.live)


# --------------------------------------------------------------------------
# Host <-> device conversion
# --------------------------------------------------------------------------

def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def pad_capacity(n: int, multiple: int = 1024) -> int:
    """Bucket row counts so jit traces are reused across similar batches
    (Trino reuses compiled PageProcessors across pages the same way)."""
    return max(multiple, _round_up(n, multiple))


def bucket_capacity(n: int) -> int:
    """Coarse capacity bucket: the smallest of {2^k, 1.5*2^k} >= n.

    Data-dependent capacities (post-compaction, join-expansion retries)
    must land on few distinct values or every query compiles fresh
    multi-minute XLA programs at large sizes; two buckets per octave caps
    padding waste at 33% while keeping the jit/persistent-cache hit rate
    high."""
    n = max(1024, int(n))
    k = (n - 1).bit_length()
    if n <= 3 << (k - 2):          # 1.5 * 2^(k-1)
        return 3 << (k - 2)
    return 1 << k


def batch_from_numpy(arrays: Sequence[np.ndarray],
                     valids: Optional[Sequence[Optional[np.ndarray]]] = None,
                     capacity: Optional[int] = None,
                     pad_multiple: int = 1024) -> Batch:
    """Build a device Batch from host numpy columns, padding to capacity."""
    n = len(arrays[0]) if len(arrays) else 0
    for a in arrays:
        assert len(a) == n, "ragged columns"
    cap = capacity if capacity is not None else pad_capacity(n, pad_multiple)
    assert cap >= n
    cols = []
    for i, a in enumerate(arrays):
        a = np.asarray(a)
        data = np.zeros(cap, dtype=a.dtype)
        data[:n] = a
        v = np.zeros(cap, dtype=np.bool_)
        if valids is not None and valids[i] is not None:
            v[:n] = valids[i]
        else:
            v[:n] = True
        cols.append(Column(data=jnp.asarray(data), valid=jnp.asarray(v)))
    live = np.zeros(cap, dtype=np.bool_)
    live[:n] = True
    return Batch(columns=tuple(cols), live=jnp.asarray(live))


def batch_to_numpy(batch: Batch) -> tuple:
    """Compact live rows back to host numpy. Returns (arrays, valids).

    One device_get for the whole pytree: per-column np.asarray would pay
    a network round trip each over a tunneled accelerator (~60ms/RTT)."""
    host = jax.device_get(batch)
    live = np.asarray(host.live)
    idx = np.nonzero(live)[0]
    arrays, valids = [], []
    for col in host.columns:
        arrays.append(np.asarray(col.data)[idx])
        valids.append(np.asarray(col.valid)[idx])
    return arrays, valids


def decode_column(field: Field, data: np.ndarray, valid: np.ndarray) -> list:
    """Render a host column to Python values (strings via dictionary,
    decimals via scale). Used at the client/protocol boundary only."""
    import datetime
    epoch = datetime.date(1970, 1, 1)
    out = []
    kind = field.dtype.kind
    for x, v in zip(data, valid):
        if not v:
            out.append(None)
        elif kind is TypeKind.VARCHAR:
            out.append(field.dictionary[int(x)])
        elif kind is TypeKind.DECIMAL:
            # exact: unscaled int64 may exceed 2^53, so float division
            # would corrupt low digits
            from decimal import Decimal
            out.append(Decimal(int(x)).scaleb(-field.dtype.scale))
        elif kind is TypeKind.DOUBLE:
            out.append(float(x))
        elif kind is TypeKind.BOOLEAN:
            out.append(bool(x))
        elif kind is TypeKind.DATE:
            out.append((epoch + datetime.timedelta(days=int(x))).isoformat())
        elif kind is TypeKind.TIMESTAMP:
            base = datetime.datetime(1970, 1, 1)
            out.append((base + datetime.timedelta(
                microseconds=int(x))).isoformat(sep=" "))
        else:
            out.append(int(x))
    return out
