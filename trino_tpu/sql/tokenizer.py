"""SQL tokenizer.

Reference: the lexer rules of core/trino-grammar's SqlBase.g4 (identifiers,
quoted identifiers, string/number literals, comments, operators). Keywords
are recognized case-insensitively; non-reserved words double as identifiers
at the parser's discretion.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List

TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*|\.\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<name>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|\|\||->|[(),.;*/%+\-<>=\[\]?])
""", re.VERBOSE | re.DOTALL)


@dataclass
class Token:
    kind: str      # 'number' | 'string' | 'name' | 'op' | 'eof'
    text: str      # names upper-cased for keyword matching
    raw: str
    pos: int


class SqlSyntaxError(Exception):
    def __init__(self, message: str, sql: str = "", pos: int = 0):
        line = sql.count("\n", 0, pos) + 1
        col = pos - (sql.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"{message} at line {line}:{col}")
        self.pos = pos


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(sql):
        m = TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlSyntaxError(f"unexpected character {sql[pos]!r}",
                                 sql, pos)
        kind = m.lastgroup
        text = m.group()
        if kind != "ws":
            if kind == "name":
                tokens.append(Token("name", text.upper(), text, pos))
            elif kind == "string":
                tokens.append(Token("string", text[1:-1].replace("''", "'"),
                                    text, pos))
            elif kind == "qident":
                tokens.append(Token("qident",
                                    text[1:-1].replace('""', '"'),
                                    text, pos))
            else:
                tokens.append(Token(kind, text, text, pos))
        pos = m.end()
    tokens.append(Token("eof", "", "", len(sql)))
    return tokens
