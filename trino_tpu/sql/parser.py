"""Recursive-descent SQL parser.

Reference: core/trino-parser/.../parser/SqlParser.java:53 drives an ANTLR
grammar (SqlBase.g4, 1,467 lines) and AstBuilder lowers to the AST. We parse
the executed subset directly — queries with joins, subqueries, aggregates,
CASE/CAST/EXTRACT/LIKE/IN/BETWEEN, ORDER BY / LIMIT, EXPLAIN — with the same
operator precedence as the reference grammar.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from . import ast_nodes as A
from .tokenizer import SqlSyntaxError, Token, tokenize

RESERVED_STOPPERS = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "ON", "JOIN",
    "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "AND", "OR", "NOT", "AS",
    "BY", "ASC", "DESC", "UNION", "EXCEPT", "INTERSECT", "SELECT", "THEN",
    "WHEN", "ELSE", "END", "IS", "IN", "LIKE", "BETWEEN", "NULLS", "FIRST",
    "LAST", "EXISTS", "CASE", "DISTINCT", "WITH",
}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens: List[Token] = tokenize(sql)
        self.i = 0

    # ---- token helpers ----------------------------------------------------

    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.i + offset, len(self.tokens) - 1)]

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "name" and t.text in words

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def advance(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.advance()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.advance()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            self.fail(f"expected {word}, found {self.peek().raw!r}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            self.fail(f"expected {op!r}, found {self.peek().raw!r}")

    def fail(self, message: str):
        raise SqlSyntaxError(message, self.sql, self.peek().pos)

    # ---- entry points -----------------------------------------------------

    def parse_statement(self) -> A.Node:
        if self.accept_kw("EXPLAIN"):
            analyze = self.accept_kw("ANALYZE")
            if self.at_kw("CREATE"):
                q: A.Node = self.parse_create_table()
            elif self.accept_kw("INSERT"):
                self.expect_kw("INTO")
                q = A.InsertInto(tuple(self.qualified_name()),
                                 self.parse_query())
            else:
                q = self.parse_query()
            node: A.Node = A.Explain(q, analyze)
        elif self.at_kw("SHOW"):
            node = self.parse_show()
        elif self.accept_kw("DESCRIBE") or self.accept_kw("DESC"):
            node = A.ShowColumns(tuple(self.qualified_name()))
        elif self.accept_kw("SET"):
            self.expect_kw("SESSION")
            name = ".".join(self.qualified_name())
            self.expect_op("=")
            node = A.SetSession(name, self.parse_expr())
        elif self.at_kw("CREATE"):
            node = self.parse_create_table()
        elif self.accept_kw("DROP"):
            self.expect_kw("TABLE")
            if_exists = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            node = A.DropTable(tuple(self.qualified_name()), if_exists)
        elif self.accept_kw("INSERT"):
            self.expect_kw("INTO")
            table = tuple(self.qualified_name())
            node = A.InsertInto(table, self.parse_query())
        elif self.accept_kw("UPDATE"):
            node = self.parse_update()
        elif self.accept_kw("DELETE"):
            self.expect_kw("FROM")
            table = tuple(self.qualified_name())
            where = self.parse_expr() if self.accept_kw("WHERE") else None
            node = A.Delete(table, where)
        elif self.accept_kw("MERGE"):
            node = self.parse_merge()
        else:
            node = self.parse_query()
        self.accept_op(";")
        if self.peek().kind != "eof":
            self.fail(f"unexpected trailing input {self.peek().raw!r}")
        return node

    def parse_create_table(self) -> A.Node:
        self.expect_kw("CREATE")
        self.expect_kw("TABLE")
        if_not_exists = False
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            if_not_exists = True
        table = tuple(self.qualified_name())
        if self.accept_kw("AS"):
            return A.CreateTable(table, (), self.parse_query(),
                                 if_not_exists)
        self.expect_op("(")
        cols = []
        while True:
            name = self.advance()
            if name.kind not in ("name", "qident"):
                self.fail("expected column name")
            cols.append((name.raw if name.kind == "qident"
                         else name.text.lower(), self.parse_type_name()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return A.CreateTable(table, tuple(cols), None, if_not_exists)

    def parse_show(self) -> A.Node:
        self.expect_kw("SHOW")
        if self.accept_kw("TABLES"):
            catalog = schema = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                parts = self.qualified_name()
                if len(parts) == 2:
                    catalog, schema = parts
                else:
                    schema = parts[0]
            return A.ShowTables(catalog, schema)
        if self.accept_kw("CATALOGS"):
            return A.ShowCatalogs()
        if self.accept_kw("SCHEMAS"):
            catalog = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                catalog = self.qualified_name()[0]
            return A.ShowSchemas(catalog)
        if self.accept_kw("SESSION"):
            return A.ShowSession()
        if self.accept_kw("COLUMNS"):
            self.expect_kw("FROM")
            return A.ShowColumns(tuple(self.qualified_name()))
        self.fail("unsupported SHOW statement")

    def parse_query(self) -> A.Node:
        """queryNoWith: WITH? set-op chain (ORDER BY)? (LIMIT)?
        (SqlBase.g4 query/queryNoWith/queryTerm structure)."""
        ctes = []
        if self.accept_kw("WITH"):
            while True:
                t = self.advance()
                if t.kind != "name":
                    self.fail("expected CTE name after WITH")
                self.expect_kw("AS")
                self.expect_op("(")
                cq = self.parse_query()
                self.expect_op(")")
                ctes.append((t.raw, cq))
                if not self.accept_op(","):
                    break

        body = self.parse_set_body()

        order_by: Tuple[A.OrderItem, ...] = ()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            items_o = [self.order_item()]
            while self.accept_op(","):
                items_o.append(self.order_item())
            order_by = tuple(items_o)

        limit = None
        if self.accept_kw("LIMIT"):
            t = self.advance()
            if t.kind != "number":
                self.fail("LIMIT expects a number")
            limit = int(t.text)

        import dataclasses
        if isinstance(body, A.Values):
            # bare VALUES statement: wrap as SELECT * FROM (VALUES ...)
            body = A.Query((A.SelectItem(expr=None),), False,
                           A.ValuesRef(body, "values"), None, (), None,
                           (), None)
        if isinstance(body, (A.Query, A.SetOp)):
            inner_has = body.order_by or body.limit is not None or body.ctes
            outer_has = order_by or limit is not None or ctes
            if not outer_has:
                return body       # parenthesized query keeps its clauses
            if inner_has:
                # both levels have clauses: outer wraps the parenthesized
                # body as a derived table so neither is lost
                body = A.Query((A.SelectItem(expr=None),), False,
                               A.SubqueryRef(body, "$sub"), None, (), None,
                               (), None)
            return dataclasses.replace(body, order_by=order_by, limit=limit,
                                       ctes=tuple(ctes))
        self.fail("malformed query body")

    def parse_set_body(self) -> A.Node:
        left = self.parse_set_term()
        while self.at_kw("UNION", "EXCEPT"):
            op = self.advance().text.lower()
            all_rows = self.accept_kw("ALL")
            if not all_rows:
                self.accept_kw("DISTINCT")
            left = A.SetOp(op, all_rows, left, self.parse_set_term())
        return left

    def parse_set_term(self) -> A.Node:
        left = self.parse_set_primary()
        while self.at_kw("INTERSECT"):
            self.advance()
            all_rows = self.accept_kw("ALL")
            if not all_rows:
                self.accept_kw("DISTINCT")
            left = A.SetOp("intersect", all_rows, left,
                           self.parse_set_primary())
        return left

    def parse_set_primary(self) -> A.Node:
        if self.accept_op("("):
            q = self.parse_query()
            self.expect_op(")")
            return q
        if self.at_kw("VALUES"):
            return self.parse_values()
        return self.parse_select_core()

    def parse_values(self) -> A.Values:
        self.expect_kw("VALUES")
        rows = []
        while True:
            if self.accept_op("("):
                row = [self.parse_expr()]
                while self.accept_op(","):
                    row.append(self.parse_expr())
                self.expect_op(")")
            else:
                row = [self.parse_expr()]
            rows.append(tuple(row))
            if not self.accept_op(","):
                break
        return A.Values(tuple(rows))

    def parse_select_core(self) -> A.Query:
        """One SELECT..HAVING block (querySpecification in SqlBase.g4);
        ORDER BY / LIMIT / WITH belong to the enclosing query."""
        self.expect_kw("SELECT")
        distinct = self.accept_kw("DISTINCT")
        self.accept_kw("ALL")
        select = [self.select_item()]
        while self.accept_op(","):
            select.append(self.select_item())

        relation = None
        if self.accept_kw("FROM"):
            relation = self.parse_relation()

        where = self.parse_expr() if self.accept_kw("WHERE") else None

        group_by: Tuple[A.Node, ...] = ()
        grouping_sets: Tuple = ()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by, grouping_sets = self.parse_group_by()

        having = self.parse_expr() if self.accept_kw("HAVING") else None

        return A.Query(tuple(select), distinct, relation, where, group_by,
                       having, (), None, (), grouping_sets)

    def parse_group_by(self):
        """GROUP BY exprs | ROLLUP(..) | CUBE(..) | GROUPING SETS((..),..).
        Returns (distinct exprs, sets of indexes into them); plain GROUP BY
        yields no sets (single implicit full set)."""
        if self.accept_kw("ROLLUP"):
            self.expect_op("(")
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            sets = tuple(tuple(range(k))
                         for k in range(len(items), -1, -1))
            return tuple(items), sets
        if self.accept_kw("CUBE"):
            self.expect_op("(")
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            n = len(items)
            sets = tuple(tuple(i for i in range(n) if mask & (1 << i))
                         for mask in range((1 << n) - 1, -1, -1))
            return tuple(items), sets
        if self.accept_kw("GROUPING"):
            self.expect_kw("SETS")
            self.expect_op("(")
            raw_sets = []
            items: list = []

            def parse_one_set():
                exprs = []
                if self.accept_op("("):
                    if not self.at_op(")"):
                        exprs.append(self.parse_expr())
                        while self.accept_op(","):
                            exprs.append(self.parse_expr())
                    self.expect_op(")")
                else:
                    exprs.append(self.parse_expr())
                idxs = []
                for e in exprs:
                    if e not in items:
                        items.append(e)
                    idxs.append(items.index(e))
                raw_sets.append(tuple(idxs))

            parse_one_set()
            while self.accept_op(","):
                parse_one_set()
            self.expect_op(")")
            return tuple(items), tuple(raw_sets)
        items = [self.parse_expr()]
        while self.accept_op(","):
            items.append(self.parse_expr())
        return tuple(items), ()

    # ---- select items / order items --------------------------------------

    def select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.advance()
            return A.SelectItem(expr=None)
        # t.* / schema.t.*
        save = self.i
        if self.peek().kind in ("name", "qident"):
            t = self.advance()
            parts = [t.raw if t.kind == "name" else t.text]
            matched_star = False
            while self.at_op("."):
                nxt = self.peek(1)
                if nxt.kind == "op" and nxt.text == "*":
                    self.advance()
                    self.advance()
                    matched_star = True
                    break
                if nxt.kind in ("name", "qident"):
                    self.advance()
                    t = self.advance()
                    parts.append(t.raw if t.kind == "name" else t.text)
                else:
                    break
            if matched_star:
                return A.SelectItem(expr=None,
                                    star_qualifier=tuple(parts))
            self.i = save
        expr = self.parse_expr()
        alias = self.maybe_alias()
        return A.SelectItem(expr=expr, alias=alias)

    def maybe_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            t = self.advance()
            if t.kind not in ("name", "qident"):
                self.fail("expected alias")
            return t.raw if t.kind == "name" else t.raw[1:-1]
        t = self.peek()
        if t.kind == "qident":
            self.advance()
            return t.text
        if t.kind == "name" and t.text not in RESERVED_STOPPERS:
            self.advance()
            return t.raw
        return None

    def order_item(self) -> A.OrderItem:
        expr = self.parse_expr()
        asc = True
        if self.accept_kw("ASC"):
            asc = True
        elif self.accept_kw("DESC"):
            asc = False
        nulls_first = None
        if self.accept_kw("NULLS"):
            if self.accept_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return A.OrderItem(expr, asc, nulls_first)

    # ---- relations --------------------------------------------------------

    def parse_update(self) -> A.Node:
        table = tuple(self.qualified_name())
        self.expect_kw("SET")
        assignments = []
        while True:
            col = self.qualified_name()[-1].lower()
            self.expect_op("=")
            assignments.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return A.Update(table, tuple(assignments), where)

    def parse_merge(self) -> A.Node:
        self.expect_kw("INTO")
        target = tuple(self.qualified_name())
        target_alias = None
        self.accept_kw("AS")
        if self.peek().kind in ("name", "qident") and \
                not self.at_kw("USING"):
            target_alias = self.qualified_name()[0].lower()
        self.expect_kw("USING")
        source = self.table_primary()
        self.expect_kw("ON")
        on = self.parse_expr()
        clauses = []
        while self.accept_kw("WHEN"):
            matched = not self.accept_kw("NOT")
            self.expect_kw("MATCHED")
            cond = self.parse_expr() if self.accept_kw("AND") else None
            self.expect_kw("THEN")
            if self.accept_kw("UPDATE"):
                self.expect_kw("SET")
                assignments = []
                while True:
                    col = self.qualified_name()[-1].lower()
                    self.expect_op("=")
                    assignments.append((col, self.parse_expr()))
                    if not self.accept_op(","):
                        break
                clauses.append(A.MergeClause(matched, cond, "update",
                                             tuple(assignments)))
            elif self.accept_kw("DELETE"):
                clauses.append(A.MergeClause(matched, cond, "delete"))
            else:
                self.expect_kw("INSERT")
                cols = []
                if self.accept_op("("):
                    while True:
                        cols.append(self.qualified_name()[-1].lower())
                        if not self.accept_op(","):
                            break
                    self.expect_op(")")
                self.expect_kw("VALUES")
                self.expect_op("(")
                vals = [self.parse_expr()]
                while self.accept_op(","):
                    vals.append(self.parse_expr())
                self.expect_op(")")
                clauses.append(A.MergeClause(matched, cond, "insert",
                                             insert_columns=tuple(cols),
                                             insert_values=tuple(vals)))
        if not clauses:
            self.fail("MERGE requires at least one WHEN clause")
        return A.MergeInto(target, target_alias, source, on,
                           tuple(clauses))

    def parse_relation(self) -> A.Node:
        left = self.join_chain()
        while self.accept_op(","):
            right = self.join_chain()
            left = A.Join("cross", left, right, None)
        return left

    def join_chain(self) -> A.Node:
        left = self.table_primary()
        while True:
            if self.accept_kw("CROSS"):
                self.expect_kw("JOIN")
                right = self.table_primary()
                left = A.Join("cross", left, right, None)
                continue
            kind = None
            if self.at_kw("JOIN") or self.at_kw("INNER"):
                self.accept_kw("INNER")
                self.expect_kw("JOIN")
                kind = "inner"
            elif self.at_kw("LEFT"):
                self.advance()
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "left"
            elif self.at_kw("RIGHT"):
                self.advance()
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "right"
            elif self.at_kw("FULL"):
                self.advance()
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "full"
            else:
                return left
            right = self.table_primary()
            self.expect_kw("ON")
            cond = self.parse_expr()
            left = A.Join(kind, left, right, cond)

    def table_primary(self) -> A.Node:
        if self.at_kw("UNNEST"):
            self.advance()
            self.expect_op("(")
            arg = self.parse_expr()
            self.expect_op(")")
            ordinality = False
            if self.accept_kw("WITH"):
                self.expect_kw("ORDINALITY")
                ordinality = True
            alias, colnames = self.table_alias_with_columns()
            return A.UnnestRef(arg, alias, colnames, ordinality)
        if self.accept_op("("):
            if self.at_kw("VALUES"):
                v = self.parse_values()
                self.expect_op(")")
                alias, colnames = self.table_alias_with_columns()
                return A.ValuesRef(v, alias or "values", colnames)
            if self.at_kw("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                self.accept_kw("AS")
                t = self.advance()
                if t.kind not in ("name", "qident"):
                    self.fail("derived table requires an alias")
                return A.SubqueryRef(q, t.raw if t.kind == "name"
                                     else t.raw[1:-1])
            if self.at_op("("):
                # '((...' — either a parenthesized set operation used as
                # a derived table, or a parenthesized join relation:
                # try the query grammar first, backtrack on failure
                mark = self.i
                try:
                    q = self.parse_query()
                    self.expect_op(")")
                    alias = self.maybe_alias()
                    return A.SubqueryRef(q, alias or "$setop")
                except SqlSyntaxError:
                    self.i = mark
            rel = self.parse_relation()
            self.expect_op(")")
            return rel
        parts = self.qualified_name()
        alias = self.maybe_alias()
        return A.TableRef(tuple(parts), alias)

    def table_alias_with_columns(self):
        """[AS] alias [(col, col, ...)] after a derived table."""
        alias = self.maybe_alias()
        colnames = None
        if alias is not None and self.accept_op("("):
            names = []
            while True:
                t = self.advance()
                if t.kind not in ("name", "qident"):
                    self.fail("expected column name in table alias")
                names.append(t.raw if t.kind == "name" else t.text)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            colnames = tuple(names)
        return alias, colnames

    def qualified_name(self) -> List[str]:
        t = self.advance()
        if t.kind not in ("name", "qident"):
            self.fail("expected name")
        parts = [t.raw if t.kind == "name" else t.text]
        while self.at_op(".") and self.peek(1).kind in ("name", "qident"):
            self.advance()
            t = self.advance()
            parts.append(t.raw if t.kind == "name" else t.text)
        return parts

    # ---- expressions (precedence climbing) --------------------------------

    def parse_expr(self) -> A.Node:
        return self.parse_or()

    def parse_or(self) -> A.Node:
        left = self.parse_and()
        while self.accept_kw("OR"):
            left = A.BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> A.Node:
        left = self.parse_not()
        while self.accept_kw("AND"):
            left = A.BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> A.Node:
        if self.accept_kw("NOT"):
            return A.UnaryOp("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> A.Node:
        left = self.parse_additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.advance().text
                if op == "!=":
                    op = "<>"
                right = self.parse_additive()
                left = A.BinaryOp(op, left, right)
                continue
            if self.at_kw("IS"):
                self.advance()
                negated = self.accept_kw("NOT")
                self.expect_kw("NULL")
                left = A.IsNullPredicate(left, negated)
                continue
            negated = False
            save = self.i
            if self.accept_kw("NOT"):
                negated = True
            if self.accept_kw("BETWEEN"):
                low = self.parse_additive()
                self.expect_kw("AND")
                high = self.parse_additive()
                left = A.BetweenPredicate(left, low, high, negated)
                continue
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = A.InSubquery(left, q, negated)
                else:
                    vals = [self.parse_expr()]
                    while self.accept_op(","):
                        vals.append(self.parse_expr())
                    self.expect_op(")")
                    left = A.InPredicate(left, tuple(vals), negated)
                continue
            if self.accept_kw("LIKE"):
                pattern = self.parse_additive()
                escape = None
                if self.accept_kw("ESCAPE"):
                    escape = self.parse_additive()
                left = A.LikePredicate(left, pattern, escape, negated)
                continue
            if negated:
                self.i = save
            return left

    def parse_additive(self) -> A.Node:
        left = self.parse_multiplicative()
        while self.at_op("+", "-") or self.at_op("||"):
            op = self.advance().text
            left = A.BinaryOp(op, left, self.parse_multiplicative())
        return left

    def parse_multiplicative(self) -> A.Node:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.advance().text
            left = A.BinaryOp(op, left, self.parse_unary())
        return left

    def parse_unary(self) -> A.Node:
        if self.accept_op("-"):
            return A.UnaryOp("-", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> A.Node:
        t = self.peek()

        if t.kind == "number":
            self.advance()
            return A.NumberLit(t.text)
        if t.kind == "string":
            self.advance()
            return A.StringLit(t.text)

        if self.accept_op("("):
            if self.at_kw("SELECT"):
                q = self.parse_query()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e

        if t.kind != "name" and t.kind != "qident":
            self.fail(f"unexpected token {t.raw!r}")

        # keyword-introduced primaries
        if self.at_kw("ARRAY") and \
                self.peek(1).kind == "op" and self.peek(1).text == "[":
            self.advance()
            self.expect_op("[")
            items = []
            if not self.at_op("]"):
                items.append(self.parse_expr())
                while self.accept_op(","):
                    items.append(self.parse_expr())
            self.expect_op("]")
            return A.ArrayLiteral(tuple(items))
        if self.accept_kw("TRUE"):
            return A.BoolLit(True)
        if self.accept_kw("FALSE"):
            return A.BoolLit(False)
        if self.accept_kw("NULL"):
            return A.NullLit()
        if self.accept_kw("DATE"):
            s = self.advance()
            if s.kind != "string":
                self.fail("DATE expects a string literal")
            return A.DateLit(s.text)
        if self.accept_kw("TIMESTAMP"):
            s = self.advance()
            if s.kind != "string":
                self.fail("TIMESTAMP expects a string literal")
            return A.TimestampLit(s.text)
        if self.accept_kw("INTERVAL"):
            neg = False
            if self.accept_op("-"):
                neg = True
            s = self.advance()
            if s.kind != "string":
                self.fail("INTERVAL expects a string literal")
            unit_t = self.advance()
            unit = unit_t.text.lower().rstrip("s")
            if unit not in ("year", "month", "day"):
                self.fail(f"unsupported interval unit {unit_t.raw!r}")
            return A.IntervalLit(int(s.text), unit, neg)
        if self.accept_kw("CASE"):
            return self.parse_case()
        if self.accept_kw("CAST"):
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            type_name = self.parse_type_name()
            self.expect_op(")")
            return A.CastExpr(e, type_name)
        if self.accept_kw("EXTRACT"):
            self.expect_op("(")
            part_t = self.advance()
            part = part_t.text.lower()
            if part not in ("year", "month", "day", "hour", "minute",
                            "second"):
                self.fail(f"unsupported EXTRACT part {part_t.raw!r}")
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect_op(")")
            return A.ExtractExpr(part, e)
        if self.accept_kw("EXISTS"):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return A.ExistsPredicate(q, negated=False)
        if self.accept_kw("SUBSTRING") or self.accept_kw("SUBSTR"):
            self.expect_op("(")
            e = self.parse_expr()
            if self.accept_kw("FROM"):
                start = self.parse_expr()
                length = self.parse_expr() if self.accept_kw("FOR") else None
            else:
                self.expect_op(",")
                start = self.parse_expr()
                length = self.parse_expr() if self.accept_op(",") else None
            self.expect_op(")")
            args = (e, start) + ((length,) if length is not None else ())
            return A.FunctionCall("substring", args)

        # function call or column reference
        if self.peek(1).kind == "op" and self.peek(1).text == "(" and \
                t.kind == "name":
            name = self.advance().text.lower()
            self.expect_op("(")
            if self.accept_op("*"):
                self.expect_op(")")
                if self.at_kw("OVER"):
                    return self.parse_over(name, (), is_star=True)
                return A.FunctionCall(name, (), is_star=True)
            distinct = self.accept_kw("DISTINCT")
            args: Tuple[A.Node, ...] = ()
            if not self.at_op(")"):
                lst = [self.parse_expr()]
                while self.accept_op(","):
                    lst.append(self.parse_expr())
                args = tuple(lst)
            self.expect_op(")")
            if self.at_kw("OVER"):
                if distinct:
                    self.fail("DISTINCT window aggregates unsupported")
                return self.parse_over(name, args, is_star=False)
            return A.FunctionCall(name, args, distinct=distinct)

        if t.kind == "name" and t.text in RESERVED_STOPPERS:
            self.fail(f"unexpected keyword {t.raw!r}")
        parts = self.qualified_name()
        return A.Identifier(tuple(parts))

    def parse_over(self, name: str, args, is_star: bool) -> A.Node:
        """OVER (PARTITION BY ... ORDER BY ... [ROWS|RANGE frame])
        (SqlBase.g4 windowSpecification)."""
        self.expect_kw("OVER")
        self.expect_op("(")
        partition: Tuple[A.Node, ...] = ()
        order: Tuple[A.OrderItem, ...] = ()
        frame = None
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            lst = [self.parse_expr()]
            while self.accept_op(","):
                lst.append(self.parse_expr())
            partition = tuple(lst)
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            items = [self.order_item()]
            while self.accept_op(","):
                items.append(self.order_item())
            order = tuple(items)
        if self.at_kw("ROWS") or self.at_kw("RANGE"):
            unit = self.advance().text.lower()

            def bound() -> str:
                if self.accept_kw("UNBOUNDED"):
                    if self.accept_kw("PRECEDING"):
                        return "unbounded_preceding"
                    self.expect_kw("FOLLOWING")
                    return "unbounded_following"
                if self.peek().kind == "number":
                    raw = self.advance().text
                    try:
                        k = int(raw)
                    except ValueError:
                        self.fail(f"frame bound must be an integer, "
                                  f"got {raw!r}")
                    if self.accept_kw("PRECEDING"):
                        return f"{k}_preceding"
                    self.expect_kw("FOLLOWING")
                    return f"{k}_following"
                self.expect_kw("CURRENT")
                self.expect_kw("ROW")
                return "current_row"

            if self.accept_kw("BETWEEN"):
                start = bound()
                self.expect_kw("AND")
                end = bound()
            else:
                start = bound()
                end = "current_row"
            frame = A.WindowFrame(unit, start, end)
        self.expect_op(")")
        return A.WindowFunc(name, args, is_star, partition, order, frame)

    def parse_case(self) -> A.Node:
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            val = self.parse_expr()
            whens.append((cond, val))
        default = self.parse_expr() if self.accept_kw("ELSE") else None
        self.expect_kw("END")
        if not whens:
            self.fail("CASE requires at least one WHEN")
        return A.CaseExpr(operand, tuple(whens), default)

    def parse_type_name(self) -> str:
        t = self.advance()
        if t.kind != "name":
            self.fail("expected type name")
        name = t.text.lower()
        if name in ("double", "bigint", "integer", "int", "boolean", "date",
                    "timestamp", "varchar", "real", "smallint", "tinyint"):
            if name == "double" and self.accept_kw("PRECISION"):
                pass
            return "double" if name == "real" else name
        if name == "decimal" or name == "numeric":
            if self.accept_op("("):
                p = int(self.advance().text)
                s = 0
                if self.accept_op(","):
                    s = int(self.advance().text)
                self.expect_op(")")
                return f"decimal({p},{s})"
            return "decimal(18,0)"
        self.fail(f"unsupported type {t.raw!r}")


def parse(sql: str) -> A.Node:
    """Parse one SQL statement (SqlParser.createStatement equivalent)."""
    return Parser(sql).parse_statement()
