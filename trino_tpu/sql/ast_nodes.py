"""Untyped SQL AST.

Reference: core/trino-parser's 296 immutable tree classes
(core/trino-parser/.../tree/). We model the subset the engine executes;
the analyzer (planner/analyzer.py) resolves names and types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class Node:
    pass


# ---- expressions ----------------------------------------------------------

@dataclass(frozen=True)
class Identifier(Node):
    parts: Tuple[str, ...]          # qualified name, original case


@dataclass(frozen=True)
class NumberLit(Node):
    text: str                       # literal text; analyzer types it


@dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclass(frozen=True)
class BoolLit(Node):
    value: bool


@dataclass(frozen=True)
class NullLit(Node):
    pass


@dataclass(frozen=True)
class DateLit(Node):
    value: str                      # ISO yyyy-mm-dd


@dataclass(frozen=True)
class TimestampLit(Node):
    value: str                      # ISO yyyy-mm-dd hh:mm:ss


@dataclass(frozen=True)
class IntervalLit(Node):
    value: int
    unit: str                       # 'year' | 'month' | 'day'
    negative: bool = False


@dataclass(frozen=True)
class BinaryOp(Node):
    op: str                         # arithmetic/comparison/'and'/'or'
    left: Node
    right: Node


@dataclass(frozen=True)
class UnaryOp(Node):
    op: str                         # '-' | '+' | 'not'
    arg: Node


@dataclass(frozen=True)
class IsNullPredicate(Node):
    arg: Node
    negated: bool


@dataclass(frozen=True)
class BetweenPredicate(Node):
    arg: Node
    low: Node
    high: Node
    negated: bool


@dataclass(frozen=True)
class InPredicate(Node):
    arg: Node
    values: Tuple[Node, ...]        # literal list; subquery variant separate
    negated: bool


@dataclass(frozen=True)
class InSubquery(Node):
    arg: Node
    query: "Query"
    negated: bool


@dataclass(frozen=True)
class ExistsPredicate(Node):
    query: "Query"
    negated: bool


@dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


@dataclass(frozen=True)
class LikePredicate(Node):
    arg: Node
    pattern: Node
    escape: Optional[Node]
    negated: bool


@dataclass(frozen=True)
class FunctionCall(Node):
    name: str                       # lower-case
    args: Tuple[Node, ...]
    distinct: bool = False
    is_star: bool = False           # count(*)


@dataclass(frozen=True)
class WindowFrame(Node):
    """ROWS/RANGE BETWEEN <start> AND <end>. Bounds are one of
    'unbounded_preceding' | 'current_row' | 'unbounded_following'."""
    unit: str                       # 'rows' | 'range'
    start: str
    end: str


@dataclass(frozen=True)
class WindowFunc(Node):
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [frame])
    (tree/WindowOperation + WindowSpecification in the reference parser)."""
    name: str                       # lower-case
    args: Tuple[Node, ...]
    is_star: bool                   # count(*) OVER ...
    partition_by: Tuple[Node, ...]
    order_by: Tuple["OrderItem", ...]
    frame: Optional[WindowFrame]


@dataclass(frozen=True)
class CastExpr(Node):
    arg: Node
    type_name: str                  # e.g. 'bigint', 'decimal(12,2)', 'date'


@dataclass(frozen=True)
class ExtractExpr(Node):
    part: str                       # 'year' | 'month' | 'day'
    arg: Node


@dataclass(frozen=True)
class CaseExpr(Node):
    operand: Optional[Node]         # simple CASE when not None
    whens: Tuple[Tuple[Node, Node], ...]
    default: Optional[Node]


# ---- relations ------------------------------------------------------------

@dataclass(frozen=True)
class TableRef(Node):
    name: Tuple[str, ...]           # possibly qualified
    alias: Optional[str] = None


@dataclass(frozen=True)
class SubqueryRef(Node):
    query: "Query"
    alias: str


@dataclass(frozen=True)
class UnnestRef(Node):
    """UNNEST(expr) [WITH ORDINALITY] [AS alias(col [, ord])] in FROM —
    a lateral expansion over the preceding relations (tree/Unnest.java)."""
    arg: Node
    alias: Optional[str] = None
    colnames: Optional[Tuple[str, ...]] = None
    ordinality: bool = False


@dataclass(frozen=True)
class ArrayLiteral(Node):
    """ARRAY[e1, e2, ...] (tree/ArrayConstructor.java)."""
    items: Tuple[Node, ...]


@dataclass(frozen=True)
class Join(Node):
    kind: str                       # 'inner'|'left'|'right'|'full'|'cross'
    left: Node
    right: Node
    condition: Optional[Node]       # ON expr (None for cross / comma)


# ---- query structure ------------------------------------------------------

@dataclass(frozen=True)
class SelectItem(Node):
    expr: Optional[Node]            # None for '*'
    alias: Optional[str] = None
    star_qualifier: Optional[Tuple[str, ...]] = None  # for t.*


@dataclass(frozen=True)
class OrderItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass(frozen=True)
class Query(Node):
    select: Tuple[SelectItem, ...]
    distinct: bool
    relation: Optional[Node]        # table tree (None: SELECT without FROM)
    where: Optional[Node]
    group_by: Tuple[Node, ...]
    having: Optional[Node]
    order_by: Tuple[OrderItem, ...]
    limit: Optional[int]
    ctes: Tuple = ()                # WITH name AS (query), ...
    grouping_sets: Tuple = ()       # sets of indexes into group_by
                                    # (ROLLUP/CUBE/GROUPING SETS); empty =
                                    # single implicit full set


@dataclass(frozen=True)
class SetOp(Node):
    """UNION / INTERSECT / EXCEPT over two query bodies. ORDER BY / LIMIT
    attached here bind to the combined result (SqlBase.g4 queryNoWith:
    queryTerm (ORDER BY ...)? (LIMIT ...)?)."""
    op: str                         # 'union' | 'intersect' | 'except'
    all_rows: bool                  # ALL vs DISTINCT
    left: Node                      # Query | SetOp | Values
    right: Node
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    ctes: Tuple = ()


@dataclass(frozen=True)
class Values(Node):
    """VALUES (row), (row), ... — an inline table (tree/Values.java)."""
    rows: Tuple[Tuple[Node, ...], ...]


@dataclass(frozen=True)
class ValuesRef(Node):
    """(VALUES ...) AS alias (col, ...) in a FROM clause."""
    values: Values
    alias: str
    column_names: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class Explain(Node):
    query: Node
    analyze: bool = False


@dataclass(frozen=True)
class ShowTables(Node):
    catalog: Optional[str] = None
    schema: Optional[str] = None


@dataclass(frozen=True)
class ShowCatalogs(Node):
    pass


@dataclass(frozen=True)
class ShowSchemas(Node):
    catalog: Optional[str] = None


@dataclass(frozen=True)
class ShowSession(Node):
    pass


@dataclass(frozen=True)
class ShowColumns(Node):
    """SHOW COLUMNS FROM t / DESCRIBE t (tree/ShowColumns.java)."""
    table: Tuple[str, ...]


@dataclass(frozen=True)
class SetSession(Node):
    name: str
    value: Node                     # literal


@dataclass(frozen=True)
class CreateTable(Node):
    """CREATE TABLE [AS query]; plain form takes (name, type) columns."""
    table: Tuple[str, ...]
    columns: Tuple = ()             # ((name, type_name), ...)
    query: Optional[Node] = None    # CTAS
    if_not_exists: bool = False


@dataclass(frozen=True)
class DropTable(Node):
    table: Tuple[str, ...]
    if_exists: bool = False


@dataclass(frozen=True)
class InsertInto(Node):
    table: Tuple[str, ...]
    query: Node                     # Query | Values


@dataclass(frozen=True)
class Update(Node):
    """UPDATE t SET c = expr, ... [WHERE pred]."""
    table: Tuple[str, ...]
    assignments: Tuple              # ((column_name, expr_ast), ...)
    where: Optional[Node]


@dataclass(frozen=True)
class Delete(Node):
    """DELETE FROM t [WHERE pred]."""
    table: Tuple[str, ...]
    where: Optional[Node]


@dataclass(frozen=True)
class MergeInto(Node):
    """MERGE INTO target [alias] USING source [alias] ON cond
    WHEN [NOT] MATCHED [AND cond] THEN UPDATE SET ... | DELETE |
    INSERT (...) VALUES (...).
    Clause order is significant (first matching clause wins, like the
    reference's MergeProcessorOperator row routing)."""
    target: Tuple[str, ...]
    target_alias: Optional[str]
    source: Node                    # relation AST (TableRef / derived)
    on: Node
    clauses: Tuple                  # tuple[MergeClause, ...]


@dataclass(frozen=True)
class MergeClause(Node):
    matched: bool
    condition: Optional[Node]       # the AND condition, if any
    action: str                     # 'update' | 'delete' | 'insert'
    assignments: Tuple = ()         # update: ((column_name, expr), ...)
    insert_columns: Tuple = ()      # insert: (column_name, ...)
    insert_values: Tuple = ()       # insert: (expr, ...)
