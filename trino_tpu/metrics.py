"""Prometheus-style metrics registry: counters, gauges, histograms.

Reference: Trino exposes its operator/task/query counters through JMX and
the /v1/status + OpenMetrics endpoints (io.airlift.stats counters wired by
ServerMainModule; the openmetrics plugin renders them in Prometheus text
exposition format). Here: one dependency-free registry shared by every
layer — executors, pageserde, scheduler, spool, HTTP servers — rendered as
Prometheus text on `GET /v1/metrics` of both coordinator and worker.

Design constraints:
- hot-path cost is one dict lookup + one float add under a lock (the
  executor increments per plan node, the serde per frame) — no metric may
  force a device sync or an allocation beyond the label-key tuple;
- metrics that acceptance checks scrape (operator rows, scheduler
  retries/hedges, CRC failures) are PRE-INITIALIZED at import so a fresh
  server renders them at 0 instead of omitting them;
- registration is idempotent: re-importing or re-declaring a metric with
  the same name returns the existing instance (kind mismatch raises).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, Tuple


def _escape(v: object) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace(
        '"', r'\"')


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        # label-value tuple -> float; unlabeled metrics live under ()
        self._values: "OrderedDict[tuple, float]" = OrderedDict()
        if not self.labelnames:
            self._values[()] = 0.0

    def _key(self, labels: Dict[str, object]) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def init_labels(self, **labels) -> None:
        """Pre-create a zero-valued sample so the label combination
        renders before its first increment (scrape-surface stability)."""
        key = self._key(labels)
        with self._lock:
            self._values.setdefault(key, 0.0)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def has_sample(self, **labels) -> bool:
        with self._lock:
            return self._key(labels) in self._values

    def _sample_line(self, key: tuple, value: float,
                     suffix: str = "", extra: tuple = ()) -> str:
        pairs = list(zip(self.labelnames, key)) + list(extra)
        labels = ",".join(f'{n}="{_escape(v)}"' for n, v in pairs)
        body = f"{{{labels}}}" if labels else ""
        if value == int(value):
            return f"{self.name}{suffix}{body} {int(value)}"
        return f"{self.name}{suffix}{body} {value}"

    def render(self) -> Iterable[str]:
        with self._lock:
            items = list(self._values.items())
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        for key, value in items:
            yield self._sample_line(key, value)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Histogram(_Metric):
    """Cumulative-bucket histogram (classic Prometheus layout):
    name_bucket{le=...}, name_sum, name_count per label set."""

    kind = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0)

    def __init__(self, name, help, labelnames, lock, buckets=None):
        super().__init__(name, help, labelnames, lock)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._values.pop((), None)       # histograms use structured slots
        self._hists: Dict[tuple, list] = {}
        if not self.labelnames:
            self._hists[()] = [0] * (len(self.buckets) + 2)

    def init_labels(self, **labels) -> None:
        """Pre-create a zeroed histogram for the label combination so it
        renders (buckets/count/sum at 0) before the first observe."""
        key = self._key(labels)
        with self._lock:
            self._hists.setdefault(key, [0] * (len(self.buckets) + 2))

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [0] * (len(self.buckets) + 2)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    h[i] += 1
            h[-2] += 1                   # count
            h[-1] += value               # sum

    def value(self, **labels) -> float:  # count, for test symmetry
        with self._lock:
            h = self._hists.get(self._key(labels))
            return h[-2] if h else 0.0

    def has_sample(self, **labels) -> bool:
        with self._lock:
            return self._key(labels) in self._hists

    def render(self) -> Iterable[str]:
        with self._lock:
            items = [(k, list(h)) for k, h in self._hists.items()]
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        for key, h in items:
            for i, b in enumerate(self.buckets):
                yield self._sample_line(key, h[i], suffix="_bucket",
                                        extra=(("le", b),))
            yield self._sample_line(key, h[-2], suffix="_bucket",
                                    extra=(("le", "+Inf"),))
            yield self._sample_line(key, h[-1], suffix="_sum")
            yield self._sample_line(key, h[-2], suffix="_count")


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "OrderedDict[str, _Metric]" = OrderedDict()

    def _register(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name} already registered as {m.kind}")
                return m
            m = cls(name, help, tuple(labelnames),
                    threading.Lock(), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def get(self, name) -> _Metric:
        with self._lock:
            return self._metrics[name]

    def render(self) -> str:
        """Full registry in Prometheus text exposition format."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[tuple, float]:
        """{(name, label-values...): value} — bench/test delta helper."""
        out = {}
        with self._lock:
            metrics = list(self._metrics.items())
        for name, m in metrics:
            if isinstance(m, Histogram):
                with m._lock:
                    for k, h in m._hists.items():
                        out[(name,) + k] = h[-2]
            else:
                with m._lock:
                    for k, v in m._values.items():
                        out[(name,) + k] = v
        return out


# ---------------------------------------------------------------------------
# the process-global registry plus the engine's metric families. In a real
# multi-host deployment each process (coordinator or worker) has its own;
# the in-process test cluster shares one, which is also what the shared
# jitted-kernel executor implies.
# ---------------------------------------------------------------------------

REGISTRY = MetricsRegistry()

# HTTP surface (both servers route through their ROUTES table)
HTTP_REQUESTS = REGISTRY.counter(
    "trino_tpu_http_requests_total",
    "HTTP requests served, by server role and route",
    ("server", "route"))

# query lifecycle (coordinator dispatcher)
QUERIES = REGISTRY.counter(
    "trino_tpu_queries_total", "Queries reaching a terminal state",
    ("state",))
QUERY_SECONDS = REGISTRY.histogram(
    "trino_tpu_query_seconds", "End-to-end query wall time (seconds)")

# executor operators (exec/executor.py — per plan-node dispatch)
OPERATOR_DISPATCHES = REGISTRY.counter(
    "trino_tpu_operator_dispatch_total",
    "Plan-node kernel dispatches, by operator", ("operator",))
OPERATOR_WALL_MS = REGISTRY.counter(
    "trino_tpu_operator_wall_ms_total",
    "Host wall-clock spent dispatching each operator (ms; async device "
    "work overlaps unless profiling)", ("operator",))
OPERATOR_ROWS = REGISTRY.counter(
    "trino_tpu_operator_rows_total",
    "Rows flowing through instrumented operators", ("operator",))
EXEC_EVENTS = REGISTRY.counter(
    "trino_tpu_exec_events_total",
    "Executor adaptive-path events mirrored from ExecStats", ("event",))

# worker task output (server/tasks.py)
TASK_OUTPUT_ROWS = REGISTRY.counter(
    "trino_tpu_task_output_rows_total",
    "Rows emitted into worker task output buffers")
TASK_OUTPUT_BYTES = REGISTRY.counter(
    "trino_tpu_task_output_bytes_total",
    "Encoded page-frame bytes emitted into worker task output buffers")

# device-resident fact cache (exec/device_cache.py)
DEVICE_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_device_cache_hits_total",
    "Fact-table device cache hits")
DEVICE_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_device_cache_misses_total",
    "Fact-table device cache misses (narrow + ingest paid)")

# scheduler (server/scheduler.py)
SCHED_TASKS = REGISTRY.counter(
    "trino_tpu_sched_tasks_total", "Remote tasks dispatched to workers")
SCHED_TASK_RETRIES = REGISTRY.counter(
    "trino_tpu_sched_task_retries_total",
    "Task-retry rounds (failed splits reassigned to survivors)")
SCHED_HEDGES = REGISTRY.counter(
    "trino_tpu_sched_hedges_total",
    "Speculative straggler re-dispatches fired")
SCHED_HEDGE_WINS = REGISTRY.counter(
    "trino_tpu_sched_hedge_wins_total",
    "Hedged attempts that beat the original task")

# page serde integrity (server/pageserde.py)
PAGE_CRC_FAILURES = REGISTRY.counter(
    "trino_tpu_pageserde_crc_failures_total",
    "Page frames rejected by the CRC32C integrity gate")

# control-plane retries (server/retrypolicy.py)
RETRY_ATTEMPTS = REGISTRY.counter(
    "trino_tpu_retry_attempts_total",
    "RetryPolicy re-attempts after a retryable failure", ("component",))

# durable exchange spool (server/exchange_spool.py)
SPOOL_HITS = REGISTRY.counter(
    "trino_tpu_spool_hits_total",
    "Exchange-spool reads satisfied from a prior attempt's output")
SPOOL_MISSES = REGISTRY.counter(
    "trino_tpu_spool_misses_total",
    "Exchange-spool reads that missed (work dispatched live)")

# memory arbitration (exec/memory.py, exec/spill.py, server/memorymanager.py)
MEMORY_RESERVED = REGISTRY.gauge(
    "trino_tpu_memory_reserved_bytes",
    "User memory reserved against each pool", ("pool",))
MEMORY_REVOCABLE = REGISTRY.gauge(
    "trino_tpu_memory_revocable_bytes",
    "Revocable (spillable) memory reserved against each pool", ("pool",))
MEMORY_REVOCATIONS = REGISTRY.counter(
    "trino_tpu_memory_revocations_total",
    "Revocation requests driven by memory pressure (spill triggers)")
MEMORY_ACCOUNTING_ERRORS = REGISTRY.counter(
    "trino_tpu_memory_accounting_errors_total",
    "Reservation double-frees / leaks detected by the pool ledger")
SPILL_BYTES = REGISTRY.counter(
    "trino_tpu_spill_bytes_total",
    "Bytes spilled to the host/disk tier by joins and aggregations")
SPILL_PARTITIONS = REGISTRY.counter(
    "trino_tpu_spill_partitions_total",
    "Radix partitions written by the spill layer")
SPILL_RETRIES = REGISTRY.counter(
    "trino_tpu_spill_retries_total",
    "Spill container write/verify failures recovered from host RAM")
QUERIES_KILLED_OOM = REGISTRY.counter(
    "trino_tpu_queries_killed_oom_total",
    "Queries killed by the cluster LowMemoryKiller")
BACKPRESSURE_WAITS = REGISTRY.counter(
    "trino_tpu_exchange_backpressure_waits_total",
    "Producer pauses because a task output buffer hit its byte bound")

# JIT-compile observability (exec/profiler.py): every jit site routes
# through the compile recorder, which mirrors into these families
JIT_COMPILES = REGISTRY.counter(
    "trino_tpu_jit_compiles_total",
    "Fresh XLA compiles detected at instrumented jit sites", ("site",))
JIT_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_jit_cache_hits_total",
    "Instrumented jit-site calls served by an already-compiled program",
    ("site",))
JIT_COMPILE_SECONDS = REGISTRY.histogram(
    "trino_tpu_jit_compile_seconds",
    "Trace+compile wall per fresh XLA compile (seconds)")

# device-time attribution (profiled dispatches: enable_profiling /
# EXPLAIN ANALYZE fence each operator, splitting wall into components)
OPERATOR_DEVICE_MS = REGISTRY.counter(
    "trino_tpu_operator_device_ms_total",
    "Fenced device-execution time per operator (ms; profiled runs only)",
    ("operator",))
OPERATOR_COMPILE_MS = REGISTRY.counter(
    "trino_tpu_operator_compile_ms_total",
    "Compile time attributed to each operator's dispatch (ms; profiled "
    "runs only)", ("operator",))

# high-concurrency serving layer (server/serving.py, exec/router.py)
PLAN_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_plan_cache_hits_total",
    "Statements served a cached logical plan (parse/plan skipped)")
PLAN_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_plan_cache_misses_total",
    "Plan-cache lookups that planned fresh")
PLAN_CACHE_EVICTIONS = REGISTRY.counter(
    "trino_tpu_plan_cache_evictions_total",
    "Plan-cache entries evicted by the LRU/byte cap")
RESULT_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_result_cache_hits_total",
    "Queries answered from the coordinator result cache")
RESULT_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_result_cache_misses_total",
    "Result-cache lookups that executed fresh")
RESULT_CACHE_INVALIDATIONS = REGISTRY.counter(
    "trino_tpu_result_cache_invalidations_total",
    "Cached pages dropped because the catalog version moved (DDL/write)")
ROUTER_DECISIONS = REGISTRY.counter(
    "trino_tpu_router_decisions_total",
    "Cost-router execution-target decisions", ("target",))
MICROBATCH_QUERIES = REGISTRY.counter(
    "trino_tpu_microbatch_queries_total",
    "Point queries coalesced into micro-batched dispatches")
MICROBATCH_BATCHES = REGISTRY.counter(
    "trino_tpu_microbatch_batches_total",
    "Micro-batch gather windows flushed as one dispatch")

# per-operator strategy decisions (exec/executor.py gate: hash vs sort
# vs direct aggregation, dense-LUT vs hybrid-hash vs merge joins)
AGG_STRATEGY_DECISIONS = REGISTRY.counter(
    "trino_tpu_agg_strategy_decisions_total",
    "Aggregation strategy picked per operator execution", ("strategy",))
JOIN_STRATEGY_DECISIONS = REGISTRY.counter(
    "trino_tpu_join_strategy_decisions_total",
    "Join strategy picked per operator execution", ("strategy",))

# mesh join distribution (parallel/dist_executor.py gate: replicate the
# build over the mesh vs hash-repartition both sides) and the batched
# dynamic-filter / repartition data plane it rides on
JOIN_DISTRIBUTION_DECISIONS = REGISTRY.counter(
    "trino_tpu_join_distribution_decisions_total",
    "Join distribution picked per mesh join execution", ("mode",))
DYNAMIC_FILTER_ROWS_PRUNED = REGISTRY.counter(
    "trino_tpu_dynamic_filter_rows_pruned_total",
    "Probe rows pruned by build-side dynamic-filter bounds before the "
    "join ran")
MESH_REPARTITION_BYTES = REGISTRY.counter(
    "trino_tpu_mesh_repartition_bytes_total",
    "Bytes moved through all_to_all repartition exchanges by "
    "mesh-partitioned joins")

# scan-path acceleration (exec/zonemap.py + exec/chunked.py prefetch):
# zone-map split/zone pruning and the double-buffered chunk pipeline
SCAN_SPLITS_PRUNED = REGISTRY.counter(
    "trino_tpu_scan_splits_pruned_total",
    "Row-range splits dropped by zone-map pruning before dispatch "
    "(server/scheduler.py)")
SCAN_ZONES_PRUNED = REGISTRY.counter(
    "trino_tpu_scan_zones_pruned_total",
    "Zone-map row ranges skipped at scan materialization "
    "(exec/zonemap.py)")
SCAN_PREFETCH_BUFFERS = REGISTRY.gauge(
    "trino_tpu_scan_prefetch_buffers_in_use",
    "Decoded+staged chunks currently held by the chunked-driver "
    "prefetch pipeline (revocable reservations)")
SCAN_PREFETCH_STALL_SECONDS = REGISTRY.counter(
    "trino_tpu_scan_prefetch_stall_seconds",
    "Seconds the chunked-driver consumer spent waiting on a chunk the "
    "prefetch worker had not staged yet")

# elastic cluster membership (server/worker.py lifecycle state machine,
# server/coordinator.py announce protocol, server/scheduler.py drain
# handoff) + per-tenant serving (server/resourcegroups.py tenant tree,
# exec/router.py fair share) + the sustained soak harness (bench --soak)
NODE_LIFECYCLE_TRANSITIONS = REGISTRY.counter(
    "trino_tpu_node_lifecycle_transitions_total",
    "Worker lifecycle transitions observed by the coordinator's node "
    "inventory, by the state entered (ACTIVE | DRAINING | DRAINED | "
    "LEFT | FAILED)", ("state",))
SPLITS_MIGRATED = REGISTRY.counter(
    "trino_tpu_splits_migrated_total",
    "Splits handed off a DRAINING node and reassigned to survivors — "
    "counted as migrations, never as task-retry failures")
TENANT_QUERIES = REGISTRY.counter(
    "trino_tpu_tenant_queries_total",
    "Queries reaching a terminal state, by resource-group tenant",
    ("tenant",))
SOAK_SLO_VIOLATIONS = REGISTRY.counter(
    "trino_tpu_soak_slo_violations_total",
    "Per-tenant p99 SLO violations observed by the sustained-soak "
    "harness (bench.py --soak)")

# cold-start elimination (exec/prewarm.py + exec/profiler.py): AOT
# pre-warming of historical plan shapes, canonicalized-shape compile
# reuse, and the compile-aware host routing window
PREWARM_COMPILES = REGISTRY.counter(
    "trino_tpu_prewarm_compiles_total",
    "Programs compiled off the query path by the prewarm engine "
    "(historical fingerprints + staged chunk shapes)")
PREWARM_HITS = REGISTRY.counter(
    "trino_tpu_prewarm_hits_total",
    "Query-path jit calls served by a program the prewarm engine had "
    "already compiled")
COMPILE_SECONDS_SAVED = REGISTRY.counter(
    "trino_tpu_compile_seconds_saved_total",
    "Estimated query-path compile seconds avoided by prewarm hits "
    "(the off-path compile wall of each program, counted once per hit)")
JIT_DISTINCT_SHAPES = REGISTRY.gauge(
    "trino_tpu_jit_distinct_shapes",
    "Distinct (fingerprint) program shapes recorded per jit site — the "
    "shape-canonicalization regression signal", ("site",))

# fused multiway star join (ops/pallas_hash.py multiway_probe +
# exec/executor.py run_multijoin): one Pallas pass probing every
# VMEM-resident dimension table, degrading dim-by-dim to the ladder
MULTIJOIN_FUSED_PROBES = REGISTRY.counter(
    "trino_tpu_multijoin_fused_probes_total",
    "Fused multiway probe kernel launches (one per fact chunk that "
    "probed >= 2 resident dimension tables in a single pass)")
MULTIJOIN_DEGRADES = REGISTRY.counter(
    "trino_tpu_multijoin_degrades_total",
    "Dimension hops evicted from the fused star probe back to the "
    "pairwise ladder, by reason", ("reason",))

# query history + latency-regression detection (server/history.py)
LATENCY_REGRESSIONS = REGISTRY.counter(
    "trino_tpu_query_latency_regressions_total",
    "Completed queries flagged as regressed vs their per-fingerprint "
    "baseline (median + MAD)")
HISTORY_RECORDS = REGISTRY.counter(
    "trino_tpu_query_history_records_total",
    "Completed-query records appended to the query history store")

# exactly-once distributed writes (server/writeprotocol.py): staged
# attempt files, manifest dedup, journal commit, orphan sweeps
WRITE_TASKS = REGISTRY.counter(
    "trino_tpu_write_tasks_total",
    "Staged write attempts produced (one per attempt file written to a "
    "table's .staging directory)")
WRITE_ATTEMPTS_DEDUPED = REGISTRY.counter(
    "trino_tpu_write_attempts_deduped_total",
    "Duplicate write attempts dropped by (stage, partition) "
    "first-success-wins manifest dedup at commit")
WRITE_COMMITS = REGISTRY.counter(
    "trino_tpu_write_commits_total",
    "Write commit-protocol outcomes", ("outcome",))
WRITE_ORPHANS_SWEPT = REGISTRY.counter(
    "trino_tpu_write_orphans_swept_total",
    "Orphaned staging files / journals removed by abort and "
    "startup-recovery sweeps")

# critical-path wall-time attribution (server/timeline.py) + the cluster
# flight recorder (server/telemetry.py): per-query phase timelines and
# the bounded delta-encoded metric ring each node samples into
TIMELINE_QUERIES = REGISTRY.counter(
    "trino_tpu_timeline_queries_total",
    "Completed queries whose wall time was attributed into phase "
    "intervals by the critical-path analyzer")
CRITICAL_PATH_SECONDS = REGISTRY.counter(
    "trino_tpu_critical_path_seconds",
    "Attributed query wall seconds, by timeline phase (sums to total "
    "query wall across phases)", ("phase",))
TELEMETRY_SAMPLES = REGISTRY.counter(
    "trino_tpu_telemetry_samples_total",
    "Flight-recorder samples taken of the process metrics registry")
TELEMETRY_RING_EVICTIONS = REGISTRY.counter(
    "trino_tpu_telemetry_ring_evictions_total",
    "Flight-recorder samples evicted to hold the ring under its byte "
    "bound")
TENANT_QUERY_SECONDS = REGISTRY.histogram(
    "trino_tpu_tenant_query_seconds",
    "End-to-end query wall time by resource-group tenant — the "
    "flight-recorder series behind the soak's p99-over-time SLO gate",
    ("tenant",),
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             15.0, 60.0))

# coordinator crash recovery (server/ledger.py): durable query ledger,
# warm-standby promotion, client-transparent query resumption
COORDINATOR_FAILOVERS = REGISTRY.counter(
    "trino_tpu_coordinator_failovers_total",
    "Coordinator promotions completed (a standby or restarted node "
    "claimed the ledger epoch and began accepting traffic)")
LEDGER_RECORDS = REGISTRY.counter(
    "trino_tpu_ledger_records_total",
    "Records appended to the durable query ledger, by record kind",
    ("kind",))
LEDGER_BYTES = REGISTRY.gauge(
    "trino_tpu_ledger_bytes",
    "Current size of the durable query ledger file")
QUERIES_RESUMED = REGISTRY.counter(
    "trino_tpu_queries_resumed_total",
    "Queries reconstructed from the ledger after a coordinator "
    "restart/failover, by resumption mode: replayed (pre-execution "
    "states re-run from admission), reattached (spooled/surviving task "
    "output reused), reexecuted (re-run from scratch; writes dedup "
    "through the commit journal)", ("mode",))

# live query observability (server/livestats.py): streaming task-stat
# heartbeats, stuck-query diagnosis, host/device busy-fraction gauges
TASK_HEARTBEATS = REGISTRY.counter(
    "trino_tpu_task_heartbeats_total",
    "Incremental live task-stat pushes (announce-piggybacked heartbeat "
    "payloads sent by workers)")
LIVE_STATS_BYTES = REGISTRY.counter(
    "trino_tpu_live_stats_bytes_total",
    "Encoded bytes of delta-encoded live task stats shipped on the "
    "heartbeat path")
STUCK_QUERIES_DIAGNOSED = REGISTRY.counter(
    "trino_tpu_stuck_queries_diagnosed_total",
    "Running queries whose live stats stopped advancing for the stuck "
    "threshold and received an automatic structured diagnosis")
NODE_BUSY_FRACTION = REGISTRY.gauge(
    "trino_tpu_node_busy_fraction",
    "Per-node busy fraction over the last heartbeat interval, by tier: "
    "device (dispatch wall / wall) and host (interpreter wall / wall) "
    "— the flight recorder samples this into system.runtime.utilization",
    ("tier",))
NODE_BUSY_MS = REGISTRY.counter(
    "trino_tpu_node_busy_ms_total",
    "Cumulative busy milliseconds by tier — the counter form of the "
    "busy-fraction gauge; per-interval deltas of this (what the flight "
    "recorder records) give the utilization series BENCH_soak emits",
    ("tier",))

# query-lifetime enforcement (deadlines, cancellation propagation,
# orphan reaping, overload admission control): coordinator-stamped
# deadlines ride every task dispatch, terminate() fans cancellation out
# to every assigned worker, workers abandon tasks their coordinator
# forgot, and overload degrades to fast rejection
QUERIES_DEADLINE_EXCEEDED = REGISTRY.counter(
    "trino_tpu_queries_deadline_exceeded_total",
    "Queries terminated because their coordinator-stamped deadline "
    "(query_max_run_time_s) expired — surfaced to clients as "
    "QUERY_EXCEEDED_RUN_TIME")
QUERIES_REJECTED = REGISTRY.counter(
    "trino_tpu_queries_rejected_total",
    "Queries rejected before execution by admission control, by reason: "
    "queue_full (resource-group queue bound), queued_deadline "
    "(query_max_queued_time_s expired while QUEUED), load_shed "
    "(coordinator overload gate)", ("reason",))
TASKS_ABANDONED = REGISTRY.counter(
    "trino_tpu_tasks_abandoned_total",
    "Worker tasks abandoned by the orphan reaper (no coordinator "
    "status pull or heartbeat ack referenced them within "
    "task_abandonment_timeout_s) — buffers and pool reservations freed")
CANCEL_PROPAGATIONS = REGISTRY.counter(
    "trino_tpu_cancel_propagations_total",
    "terminate() fan-outs run by the coordinator, by trigger: user "
    "(client DELETE), deadline, queued_deadline, oom (low-memory "
    "killer), stuck (diagnoser escalation)", ("reason",))
RETRY_BUDGET_EXHAUSTED = REGISTRY.counter(
    "trino_tpu_retry_budget_exhausted_total",
    "Queries failed because their per-query retry/hedge amplification "
    "budget ran out — the anti-retry-storm valve under sustained chaos")
MICROBATCH_FOLLOWER_TIMEOUTS = REGISTRY.counter(
    "trino_tpu_microbatch_follower_timeouts_total",
    "Micro-batch followers that stopped waiting on their window leader "
    "(leader dead/slow, query canceled, or deadline expired) and "
    "degraded to an individual run")
BACKPRESSURE_DEADLINE_DEGRADES = REGISTRY.counter(
    "trino_tpu_backpressure_deadline_degrades_total",
    "Exchange backpressure waits that hit their (deadline-capped) "
    "bound and degraded to unbounded buffering — logged with the "
    "owning query so the silent 300 s degrade is observable")

# the labeled families acceptance scrapes: seed the hot label values so
# a cold server's /v1/metrics already carries them at 0
for _op in ("scan", "output"):
    OPERATOR_ROWS.init_labels(operator=_op)
RETRY_ATTEMPTS.init_labels(component="announce")
MEMORY_RESERVED.init_labels(pool="general")
MEMORY_REVOCABLE.init_labels(pool="general")
for _site in ("exec.fused_chunk", "exec.slice_widen"):
    JIT_COMPILES.init_labels(site=_site)
    JIT_CACHE_HITS.init_labels(site=_site)
    JIT_DISTINCT_SHAPES.init_labels(site=_site)
for _op in ("ScanNode", "JoinNode", "AggregateNode"):
    OPERATOR_DEVICE_MS.init_labels(operator=_op)
    OPERATOR_COMPILE_MS.init_labels(operator=_op)
for _target in ("host", "device"):
    ROUTER_DECISIONS.init_labels(target=_target)
for _s in ("global", "direct", "mxu", "sort", "hash"):
    AGG_STRATEGY_DECISIONS.init_labels(strategy=_s)
for _s in ("dense-lut", "hybrid-hash", "sort-merge", "sorted", "expand",
           "multiway", "ladder"):
    JOIN_STRATEGY_DECISIONS.init_labels(strategy=_s)
for _r in ("kernel_off", "vmem", "dup", "escape", "dtype", "mesh",
           "spill"):
    MULTIJOIN_DEGRADES.init_labels(reason=_r)
for _m in ("broadcast", "partitioned"):
    JOIN_DISTRIBUTION_DECISIONS.init_labels(mode=_m)
for _ls in ("ACTIVE", "DRAINING", "DRAINED", "LEFT", "FAILED"):
    NODE_LIFECYCLE_TRANSITIONS.init_labels(state=_ls)
TENANT_QUERIES.init_labels(tenant="default")
for _o in ("committed", "aborted"):
    WRITE_COMMITS.init_labels(outcome=_o)
# kept in sync with server/timeline.py PHASES (asserted in tier-1)
for _p in ("queued", "plan", "schedule", "exchange-wait", "device",
           "host", "compile", "spill", "retry", "write-commit", "other"):
    CRITICAL_PATH_SECONDS.init_labels(phase=_p)
TENANT_QUERY_SECONDS.init_labels(tenant="default")
for _k in ("admit", "state", "assign", "spool", "terminal", "catalog",
           "promote"):
    LEDGER_RECORDS.init_labels(kind=_k)
for _m in ("replayed", "reattached", "reexecuted"):
    QUERIES_RESUMED.init_labels(mode=_m)
for _t in ("device", "host"):
    NODE_BUSY_FRACTION.init_labels(tier=_t)
    NODE_BUSY_MS.init_labels(tier=_t)
for _r in ("queue_full", "queued_deadline", "load_shed"):
    QUERIES_REJECTED.init_labels(reason=_r)
for _r in ("user", "deadline", "queued_deadline", "oom", "stuck"):
    CANCEL_PROPAGATIONS.init_labels(reason=_r)
