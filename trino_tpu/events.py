"""Event listener SPI.

Reference: spi/eventlistener (QueryCreatedEvent / QueryCompletedEvent /
SplitCompletedEvent) dispatched by EventListenerManager
(eventlistener/EventListenerManager.java:56) to plugins (http, kafka,
mysql, openlineage). Here: the same contract as a Python protocol; the
coordinator dispatches on query creation and completion. Completion events
carry the distributed execution rollup (stages/tasks/bytes shuffled/faults
survived) so a listener can build billing or SLO pipelines without
scraping /v1/query.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import List, Optional

log = logging.getLogger("trino_tpu.events")


@dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    user: str
    sql: str
    create_time: float
    tenant: str = "default"       # resource-group tenant (audit label)


@dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    user: str
    sql: str
    state: str                    # FINISHED | FAILED | CANCELED
    error: Optional[str]
    elapsed_s: float
    rows: int
    retries: int
    end_time: float
    # distributed-execution rollup (0 when the query ran coordinator-local)
    stages: int = 0
    tasks: int = 0
    bytes_shuffled: int = 0
    faults_survived: int = 0      # task retries + checksum rejections
    hedges_fired: int = 0
    spills: int = 0               # spill-tier activations (history +
                                  # regression-detector input)
    tenant: str = "default"       # resource-group tenant (audit label)
    # exactly-once write rollup (zero/empty for read queries)
    written_rows: int = 0
    written_bytes: int = 0
    commit_phase: str = ""        # "committed" | "aborted" | ""
    # critical-path attribution (server/timeline.py): the phase holding
    # the most elapsed wall, "" when no timeline was built
    dominant_phase: str = ""
    # live observability (server/livestats.py): the last split-weighted
    # progress the heartbeat fold computed (1.0 for FINISHED; an
    # OOM-killed query records how far it got) and the in-flight stage
    # that held the most remaining work when the query ended
    progress_ratio: float = 0.0
    dominant_stage: str = ""


class EventListener:
    """Subclass and override; both hooks are optional (the SPI's default
    methods)."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: List[EventListener] = []
        self._logged: set = set()

    def register(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def _dispatch(self, hook: str, ev) -> None:
        for li in self._listeners:
            try:
                getattr(li, hook)(ev)
            except Exception:   # listener failures never kill queries —
                # but a silently broken listener is undiagnosable, so log
                # the first failure of each (listener, hook) pair
                key = (id(li), hook)
                if key not in self._logged:
                    self._logged.add(key)
                    log.exception(
                        "event listener %s failed in %s "
                        "(further failures suppressed)",
                        type(li).__name__, hook)

    def query_created(self, tq) -> None:
        ev = QueryCreatedEvent(tq.query_id, tq.session_user, tq.sql,
                               time.time(),
                               tenant=getattr(tq, "tenant", "default"))
        self._dispatch("query_created", ev)

    def query_completed(self, tq) -> None:
        st = getattr(tq, "stage_stats", None) or {}
        ev = QueryCompletedEvent(
            tq.query_id, tq.session_user, tq.sql, tq.state,
            tq.state_machine.error, tq.elapsed_s, tq.rows_returned,
            tq.retries, time.time(),
            stages=int(st.get("stages", 0)),
            tasks=len(st.get("tasks", ())),
            bytes_shuffled=int(st.get("bytes_shuffled", 0)),
            faults_survived=int(st.get("faults_survived", 0)),
            hedges_fired=int(st.get("hedged_tasks", 0)),
            spills=int(getattr(tq, "spills", 0)),
            tenant=getattr(tq, "tenant", "default"),
            written_rows=int((st.get("write") or {}).get("rows", 0)),
            written_bytes=int((st.get("write") or {}).get("bytes", 0)),
            commit_phase=(st.get("write") or {}).get("phase", ""),
            dominant_phase=(getattr(tq, "timeline", None) or
                            {}).get("dominant", ""),
            progress_ratio=(1.0 if tq.state == "FINISHED" else
                            float(getattr(tq, "progress_ratio", 0.0))),
            dominant_stage=getattr(tq, "dominant_stage", ""))
        self._dispatch("query_completed", ev)
