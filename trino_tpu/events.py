"""Event listener SPI.

Reference: spi/eventlistener (QueryCreatedEvent / QueryCompletedEvent /
SplitCompletedEvent) dispatched by EventListenerManager
(eventlistener/EventListenerManager.java:56) to plugins (http, kafka,
mysql, openlineage). Here: the same contract as a Python protocol; the
coordinator dispatches on query creation and completion.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    user: str
    sql: str
    create_time: float


@dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    user: str
    sql: str
    state: str                    # FINISHED | FAILED | CANCELED
    error: Optional[str]
    elapsed_s: float
    rows: int
    retries: int
    end_time: float


class EventListener:
    """Subclass and override; both hooks are optional (the SPI's default
    methods)."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: List[EventListener] = []

    def register(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def query_created(self, tq) -> None:
        ev = QueryCreatedEvent(tq.query_id, tq.session_user, tq.sql,
                               time.time())
        for li in self._listeners:
            try:
                li.query_created(ev)
            except Exception:          # listener failures never kill queries
                pass

    def query_completed(self, tq) -> None:
        ev = QueryCompletedEvent(
            tq.query_id, tq.session_user, tq.sql, tq.state,
            tq.state_machine.error, tq.elapsed_s, tq.rows_returned,
            tq.retries, time.time())
        for li in self._listeners:
            try:
                li.query_completed(ev)
            except Exception:
                pass
