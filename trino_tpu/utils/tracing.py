"""Distributed span tracing with W3C trace-context propagation.

Reference: Trino wires OpenTelemetry spans through the whole query path —
TracingModule at bootstrap (server/Server.java:106), spans around planning
(SqlQueryExecution.java:473,501), split scheduling
(split/SplitManager.java:85), decorators like tracing/TracingMetadata.java,
semantic attributes in tracing/TrinoAttributes.java — and propagates the
context over every internal HTTP hop so one query yields one trace.

Here: a dependency-free tracer with the same shape — named spans with
attributes and random 64-bit span ids, parent/child nesting via a
thread-local context stack, a W3C `traceparent` header
(`00-<trace_id>-<span_id>-01`) carried on every internal hop (statement
POST, task create, exchange pulls, spooled-segment gets), and remote spans
adopted back into the originating tracer so the coordinator can serve the
stitched query trace as OTLP-like JSON. Disabled tracers are zero-overhead
no-ops.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_ROOT_SPAN_ID = "0" * 16


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """-> (trace_id, parent_span_id) or None on anything malformed."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


@dataclass
class Span:
    name: str
    start: float                       # time.monotonic()
    end: Optional[float] = None
    attributes: Dict[str, object] = field(default_factory=dict)
    trace_id: str = ""
    span_id: str = ""
    # parent SPAN ID (not name: one query spawns many same-named task
    # spans, so a name link is ambiguous); None = trace root
    parent_id: Optional[str] = None
    service: str = "trino-tpu"
    start_unix: float = 0.0            # time.time() at start

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.monotonic()) - self.start) * 1000

    def to_dict(self) -> dict:
        return {"name": self.name,
                "traceId": self.trace_id,
                "spanId": self.span_id,
                "parentSpanId": self.parent_id,
                "service": self.service,
                "startTimeUnixNano": int(self.start_unix * 1e9),
                "durationMs": round(self.duration_ms, 3),
                "attributes": self.attributes}


class Tracer:
    """Collects spans per thread; `span()` nests via a context stack.

    A tracer created via `from_traceparent` roots its first spans under
    the remote parent, so worker-side spans stitch under the coordinator
    span that dispatched the task. `adopt()` merges spans shipped back
    from remote processes (already-exported dicts) into this tracer's
    trace.
    """

    def __init__(self, enabled: bool = True,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 service: str = "trino-tpu"):
        self.enabled = enabled
        self.trace_id = trace_id or new_trace_id()
        self.remote_parent = parent_span_id
        self.service = service
        self.spans: List[Span] = []
        self._foreign: List[dict] = []
        self._local = threading.local()
        self._lock = threading.Lock()

    @classmethod
    def from_traceparent(cls, header: Optional[str],
                         enabled: bool = True,
                         service: str = "trino-tpu") -> "Tracer":
        ctx = parse_traceparent(header)
        if ctx is None:
            return cls(enabled=enabled, service=service)
        return cls(enabled=enabled, trace_id=ctx[0],
                   parent_span_id=ctx[1], service=service)

    def _stack(self) -> list:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def traceparent(self) -> Optional[str]:
        """Header value for the CURRENT context (innermost open span on
        this thread, else the adopted remote parent). None when tracing
        is off, so callers can skip the header entirely."""
        if not self.enabled:
            return None
        stack = self._stack()
        sid = stack[-1].span_id if stack else \
            (self.remote_parent or _ROOT_SPAN_ID)
        return format_traceparent(self.trace_id, sid)

    @contextmanager
    def span(self, name: str, **attributes):
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        parent = stack[-1].span_id if stack else self.remote_parent
        s = Span(name, time.monotonic(), attributes=dict(attributes),
                 trace_id=self.trace_id, span_id=new_span_id(),
                 parent_id=parent, service=self.service,
                 start_unix=time.time())
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.monotonic()
            stack.pop()
            with self._lock:
                self.spans.append(s)

    def adopt(self, span_dicts, offset_s: float = 0.0) -> None:
        """Merge remote spans (exported dicts shipped back in task
        results) into this trace. Spans from another trace id are kept
        too — a mis-stitched span is more diagnosable than a dropped
        one.

        `offset_s` is the remote node's estimated clock offset (remote
        clock minus local clock, measured at announce time): remote
        `startTimeUnixNano` stamps are rebased onto the local clock so
        cross-node timeline intervals cannot go negative when a worker's
        wall clock is skewed. Spans are copied, not mutated in place."""
        if not self.enabled or not span_dicts:
            return
        adopted = []
        for d in span_dicts:
            if not isinstance(d, dict):
                continue
            if offset_s and "startTimeUnixNano" in d:
                d = dict(d)
                d["startTimeUnixNano"] = int(
                    d["startTimeUnixNano"] - offset_s * 1e9)
            adopted.append(d)
        with self._lock:
            self._foreign.extend(adopted)

    def export(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in self.spans] + list(self._foreign)

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self._foreign.clear()


NOOP = Tracer(enabled=False)
