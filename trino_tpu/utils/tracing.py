"""Span tracing.

Reference: Trino wires OpenTelemetry spans through the whole query path —
TracingModule at bootstrap (server/Server.java:106), spans around planning
(SqlQueryExecution.java:473,501), split scheduling
(split/SplitManager.java:85), decorators like tracing/TracingMetadata.java,
semantic attributes in tracing/TrinoAttributes.java.

Here: a dependency-free tracer with the same shape — named spans with
attributes, parent/child nesting via a context stack, exportable as JSON
(OTLP-like dicts) or injectable into any OpenTelemetry SDK by swapping the
tracer object. Disabled tracers are zero-overhead no-ops.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Span:
    name: str
    start: float
    end: Optional[float] = None
    attributes: Dict[str, object] = field(default_factory=dict)
    parent: Optional[str] = None
    span_id: int = 0

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.monotonic()) - self.start) * 1000

    def to_dict(self) -> dict:
        return {"name": self.name, "spanId": self.span_id,
                "parent": self.parent,
                "durationMs": round(self.duration_ms, 3),
                "attributes": self.attributes}


class Tracer:
    """Collects spans per thread; `span()` nests via a context stack."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.spans: List[Span] = []
        self._local = threading.local()
        self._lock = threading.Lock()
        self._seq = 0

    def _stack(self) -> list:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextmanager
    def span(self, name: str, **attributes):
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        parent = stack[-1].name if stack else None
        with self._lock:
            self._seq += 1
            sid = self._seq
        s = Span(name, time.monotonic(), attributes=dict(attributes),
                 parent=parent, span_id=sid)
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.monotonic()
            stack.pop()
            with self._lock:
                self.spans.append(s)

    def export(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in self.spans]

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()


NOOP = Tracer(enabled=False)
