"""Structured log correlation: one consistent query/trace prefix.

Reference: Trino stamps query ids into its log lines so `grep <queryId>`
reconstructs a query's server-side story. The ad-hoc log lines here
(serving replans, memory-manager kills, prewarm, the write protocol,
slow-query warnings) grew without a shared convention, so a timeline
entry could not be grepped to its logs. No new framework — just a helper
producing the canonical `query=<id> trace=<id>` prefix every correlated
line starts with.
"""

from __future__ import annotations

from typing import Optional


def query_context(query_id: Optional[str] = None,
                  trace_id: Optional[str] = None) -> str:
    """`query=<id> trace=<id> ` prefix (trailing space included); empty
    string when neither id is known, so callers can prepend it
    unconditionally."""
    parts = []
    if query_id:
        parts.append(f"query={query_id}")
    if trace_id:
        parts.append(f"trace={trace_id}")
    return (" ".join(parts) + " ") if parts else ""


def tq_context(tq) -> str:
    """Prefix for a TrackedQuery: query id plus its tracer's trace id
    when tracing is on."""
    tracer = getattr(tq, "tracer", None)
    trace_id = getattr(tracer, "trace_id", None) if tracer is not None \
        and getattr(tracer, "enabled", False) else None
    return query_context(getattr(tq, "query_id", None), trace_id)
