"""Crash-safe file writes (temp + fsync + atomic rename).

The diskcache connector established the pattern (connectors/diskcache.py):
never let a reader observe a torn file. Writers materialize the full byte
body into a same-directory temp name, fsync the file, rename it over the
destination, then fsync the parent directory so the rename itself is
durable. A crash at any point leaves either the old file, no file, or a
dot-prefixed temp that directory scans skip — never a truncated table.
"""

import os
import tempfile


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` so a crash can never expose a prefix."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp.",
                               suffix=os.path.basename(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (renames, unlinks)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
