"""Expression evaluation: IR -> traced JAX ops (filter + project).

This is the replacement for Trino's runtime bytecode generation tier:
ExpressionCompiler/PageFunctionCompiler emit a per-query PageProcessor class
(sql/gen/ExpressionCompiler.java:38, sql/gen/PageFunctionCompiler.java:103,
operator/project/PageProcessor.java:56); we trace the expression tree into
the enclosing jitted stage program and let XLA fuse the elementwise chain
into the surrounding matmuls/reductions — codegen for free.

Every expression evaluates to ``(data, valid)`` with SQL three-valued logic:
- arithmetic/comparison: result valid = all inputs valid
- AND/OR: Kleene logic (Trino sql/ir/Logical.java semantics)
- filters treat NULL as false (WHERE semantics)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..exec.profiler import recorded_jit

from .. import ir
from ..batch import Batch, Column
from ..types import TypeKind

# --------------------------------------------------------------------------
# decimal rescaling (Trino HALF_UP semantics, DecimalConversions.java)
# --------------------------------------------------------------------------


def rescale(data: jax.Array, from_scale: int, to_scale: int,
            xp=jnp) -> jax.Array:
    """`xp` selects the array namespace (jnp on device, np for the
    host-routed point-query path in exec/router.py) so the HALF_UP
    rounding rule cannot drift between the two executions."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    d = 10 ** (from_scale - to_scale)
    half = d // 2
    # round half away from zero, like Trino's HALF_UP
    pos = (data + half) // d
    neg = -((-data + half) // d)
    return xp.where(data >= 0, pos, neg)


_FLIPPED_CMP = {'<': '>', '<=': '>=', '>': '<', '>=': '<=',
                '=': '=', '<>': '<>'}


def _decimal_compare(a: jax.Array, sa: int, b: jax.Array, sb: int,
                     op: str, xp=jnp) -> jax.Array:
    """Exact comparison of scaled-int64 decimals at different scales.

    Never multiplies either operand: the larger-scale side is split into
    (hi, lo) by floor division, and ``a <op> b/10^k`` is decided from
    ``a`` vs ``hi`` plus the sign of ``lo`` — int64-overflow-free where
    ``a * 10^k`` would wrap (Trino compares on Int128, Decimals.java).
    `xp` is unused (pure operators) but accepted for symmetry with the
    other shared helpers the host router path calls."""
    if sa == sb:
        return _apply_cmp(op, a, b)
    if sa > sb:
        return _decimal_compare(b, sb, a, sa, _FLIPPED_CMP[op], xp)
    d = 10 ** (sb - sa)
    hi = b // d                      # floor div: lo is always in [0, d)
    lo = b - hi * d
    eq0 = lo == 0
    if op == '=':
        return (a == hi) & eq0
    if op == '<>':
        return (a != hi) | ~eq0
    if op == '>':                    # a > hi + lo/d  <=>  a > hi
        return a > hi
    if op == '>=':
        return (a > hi) | ((a == hi) & eq0)
    if op == '<':
        return (a < hi) | ((a == hi) & ~eq0)
    return a <= hi                   # '<='


def _apply_cmp(op: str, l: jax.Array, r: jax.Array) -> jax.Array:
    if op == '=':
        return l == r
    if op == '<>':
        return l != r
    if op == '<':
        return l < r
    if op == '<=':
        return l <= r
    if op == '>':
        return l > r
    return l >= r


def _to_comparable(expr: ir.Expr, data: jax.Array, target,
                   xp=jnp) -> jax.Array:
    """Rescale/convert one comparison operand to the common type."""
    t = expr.dtype
    # DECIMAL comparison targets never reach here: eval_expr routes them
    # through _decimal_compare (upscaling to a common scale wraps int64)
    assert target.kind is not TypeKind.DECIMAL
    if target.kind is TypeKind.DOUBLE:
        if t.kind is TypeKind.DECIMAL:
            return data.astype(xp.float64) / (10 ** t.scale)
        return data.astype(xp.float64)
    if target.kind is TypeKind.TIMESTAMP and t.kind is TypeKind.DATE:
        return data.astype(xp.int64) * 86_400_000_000
    return data


# --------------------------------------------------------------------------
# date decomposition (days since epoch -> civil), Hinnant's algorithm —
# branch-free integer math, vectorizes cleanly on TPU
# --------------------------------------------------------------------------


def days_from_civil(y: jax.Array, m: jax.Array, d) -> jax.Array:
    """Inverse of civil_from_days (Hinnant), for date_trunc
    reconstruction."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def civil_from_days(days: jax.Array):
    z = days.astype(jnp.int64) + 719468
    # floor division is already era-correct for negative z (the C++ original
    # adjusts by -146096 only because C++ division truncates)
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


# --------------------------------------------------------------------------
# evaluator
# --------------------------------------------------------------------------


def eval_expr(expr: ir.Expr, batch: Batch):
    """Evaluate an IR expression over a batch. Returns (data, valid)."""
    n = batch.capacity

    if isinstance(expr, ir.ColumnRef):
        col = batch.columns[expr.index]
        return col.data, col.valid

    if isinstance(expr, ir.Literal):
        if expr.value is None:
            z = jnp.zeros(n, dtype=expr.dtype.np_dtype)
            return z, jnp.zeros(n, dtype=jnp.bool_)
        if expr.dtype.kind is TypeKind.VARCHAR:
            # string literal: code 0 into its single-entry pool (the
            # planner attaches the dictionary via field_for)
            return (jnp.zeros(n, dtype=jnp.int32),
                    jnp.ones(n, dtype=jnp.bool_))
        v = jnp.full(n, expr.value, dtype=expr.dtype.np_dtype)
        return v, jnp.ones(n, dtype=jnp.bool_)

    if isinstance(expr, ir.Arith):
        ld, lv = eval_expr(expr.left, batch)
        rd, rv = eval_expr(expr.right, batch)
        valid = lv & rv
        out = expr.dtype
        lt, rt = expr.left.dtype, expr.right.dtype
        if out.kind is TypeKind.DECIMAL:
            if expr.op == '*':
                res = ld.astype(jnp.int64) * rd.astype(jnp.int64)
            else:
                l = rescale(ld, lt.scale, out.scale) if lt.kind is TypeKind.DECIMAL \
                    else ld.astype(jnp.int64) * (10 ** out.scale)
                r = rescale(rd, rt.scale, out.scale) if rt.kind is TypeKind.DECIMAL \
                    else rd.astype(jnp.int64) * (10 ** out.scale)
                res = l + r if expr.op == '+' else l - r
            return res, valid
        if out.kind is TypeKind.DOUBLE:
            l = _to_comparable(expr.left, ld, out)
            r = _to_comparable(expr.right, rd, out)
            if expr.op == '+':
                res = l + r
            elif expr.op == '-':
                res = l - r
            elif expr.op == '*':
                res = l * r
            else:
                # division by zero yields NULL (documented deviation: Trino
                # raises DIVISION_BY_ZERO; a vectorized engine can't raise
                # per-row, so we degrade to NULL rather than emit a bogus
                # value marked valid)
                res = l / jnp.where(r == 0, jnp.float64(1), r)
                valid = valid & (r != 0)
            return res, valid
        # integer-like (BIGINT/INTEGER/DATE)
        l = ld.astype(out.np_dtype)
        r = rd.astype(out.np_dtype)
        if expr.op == '+':
            res = l + r
        elif expr.op == '-':
            res = l - r
        elif expr.op == '*':
            res = l * r
        else:
            # SQL integer division truncates toward zero; // floors.
            safe_r = jnp.where(r == 0, jnp.ones_like(r), r)
            q = l // safe_r
            rem = l - q * safe_r
            q = q + jnp.where((rem != 0) & ((l < 0) != (r < 0)), 1, 0
                              ).astype(q.dtype)
            res = q
            valid = valid & (r != 0)  # NULL on div-by-zero (see above)
        return res, valid

    if isinstance(expr, ir.Negate):
        d, v = eval_expr(expr.arg, batch)
        return -d, v

    if isinstance(expr, ir.Compare):
        target = ir.comparable(expr.left, expr.right)
        ld, lv = eval_expr(expr.left, batch)
        rd, rv = eval_expr(expr.right, batch)
        op = expr.op
        if target.kind is TypeKind.DECIMAL:
            # exact scaled-int comparison without upscaling either side
            # (rescaling a decimal(p,2) column to scale 12 multiplies by
            # 1e10 and silently wraps int64 — TPC-H q11's HAVING)
            sa = expr.left.dtype.scale \
                if expr.left.dtype.kind is TypeKind.DECIMAL else 0
            sb = expr.right.dtype.scale \
                if expr.right.dtype.kind is TypeKind.DECIMAL else 0
            res = _decimal_compare(ld.astype(jnp.int64), sa,
                                   rd.astype(jnp.int64), sb, op)
            return res, lv & rv
        l = _to_comparable(expr.left, ld, target)
        r = _to_comparable(expr.right, rd, target)
        return _apply_cmp(op, l, r), lv & rv

    if isinstance(expr, ir.Logical):
        parts = [eval_expr(a, batch) for a in expr.args]
        d, v = parts[0]
        for (d2, v2) in parts[1:]:
            if expr.op == 'and':
                # Kleene AND: false dominates null
                out_v = (v & v2) | (v & ~d) | (v2 & ~d2)
                d = d & d2
            else:
                out_v = (v & v2) | (v & d) | (v2 & d2)
                d = d | d2
            v = out_v
        return d, v

    if isinstance(expr, ir.Not):
        d, v = eval_expr(expr.arg, batch)
        return ~d, v

    if isinstance(expr, ir.IsNull):
        d, v = eval_expr(expr.arg, batch)
        res = v if expr.negated else ~v
        return res, jnp.ones_like(v)

    if isinstance(expr, ir.InList):
        d, v = eval_expr(expr.arg, batch)
        res = jnp.zeros_like(v)
        for lit in expr.values:
            res = res | (d == jnp.asarray(lit.value, dtype=d.dtype))
        return res, v

    if isinstance(expr, ir.Between):
        # x BETWEEN lo AND hi == (x >= lo) AND (x <= hi) with Kleene AND
        # (Trino rewrites the same way), so a definite FALSE on one side
        # dominates a NULL on the other.
        lowered = ir.Logical('and', (
            ir.Compare('>=', expr.arg, expr.low),
            ir.Compare('<=', expr.arg, expr.high),
        ))
        return eval_expr(lowered, batch)

    if isinstance(expr, ir.Case):
        default = expr.default
        if default is not None:
            acc_d, acc_v = eval_expr(default, batch)
            acc_d = acc_d.astype(expr.dtype.np_dtype)
        else:
            acc_d = jnp.zeros(n, dtype=expr.dtype.np_dtype)
            acc_v = jnp.zeros(n, dtype=jnp.bool_)
        # reverse order: first matching WHEN wins
        for cond, val in reversed(expr.whens):
            cd, cv = eval_expr(cond, batch)
            vd, vv = eval_expr(val, batch)
            take = cd & cv
            acc_d = jnp.where(take, vd.astype(expr.dtype.np_dtype), acc_d)
            acc_v = jnp.where(take, vv, acc_v)
        return acc_d, acc_v

    if isinstance(expr, ir.Cast):
        d, v = eval_expr(expr.arg, batch)
        src, dst = expr.arg.dtype, expr.dtype
        if src == dst:
            return d, v
        if dst.kind is TypeKind.DECIMAL:
            if src.kind is TypeKind.DECIMAL:
                return rescale(d, src.scale, dst.scale), v
            if src.kind is TypeKind.DOUBLE:
                # HALF_UP (away from zero), matching rescale(); jnp.round is
                # half-to-even and would disagree at *.5
                xs = d.astype(jnp.float64) * (10 ** dst.scale)
                half_up = jnp.where(xs >= 0, jnp.floor(xs + 0.5),
                                    jnp.ceil(xs - 0.5))
                return half_up.astype(jnp.int64), v
            return d.astype(jnp.int64) * (10 ** dst.scale), v
        if dst.kind is TypeKind.DOUBLE:
            if src.kind is TypeKind.DECIMAL:
                return d.astype(jnp.float64) / (10 ** src.scale), v
            return d.astype(jnp.float64), v
        if dst.kind in (TypeKind.BIGINT, TypeKind.INTEGER):
            if src.kind is TypeKind.DECIMAL:
                return rescale(d, src.scale, 0).astype(dst.np_dtype), v
            return d.astype(dst.np_dtype), v
        if dst.kind is TypeKind.DATE:
            if src.kind is TypeKind.TIMESTAMP:
                return (d // 86_400_000_000).astype(jnp.int32), v
            return d.astype(jnp.int32), v
        if dst.kind is TypeKind.TIMESTAMP:
            if src.kind is TypeKind.DATE:
                return d.astype(jnp.int64) * 86_400_000_000, v
            return d.astype(jnp.int64), v
        raise NotImplementedError(f"cast {src} -> {dst}")

    if isinstance(expr, ir.ArrayConst):
        return (jnp.zeros(n, dtype=jnp.int32),
                jnp.ones(n, dtype=jnp.bool_))

    if isinstance(expr, ir.DerivedDict):
        d, v = eval_expr(expr.arg, batch)
        lut = jnp.asarray(expr.lut, dtype=jnp.int32)
        codes = jnp.clip(d.astype(jnp.int32), 0, len(expr.lut) - 1)
        out = lut[codes]
        if expr.null_code is not None:    # varchar coalesce-to-literal
            out = jnp.where(v, out, jnp.int32(expr.null_code))
            v = jnp.ones_like(v)
        return out, v

    if isinstance(expr, ir.DictPredicate):
        d, v = eval_expr(expr.arg, batch)
        if len(expr.lut) == 0:      # empty pool: no code can match
            return jnp.zeros_like(d, dtype=jnp.bool_), v
        lut = jnp.asarray(expr.lut, dtype=jnp.bool_)
        codes = jnp.clip(d.astype(jnp.int32), 0, len(expr.lut) - 1)
        return lut[codes], v

    if isinstance(expr, ir.DecimalAvg):
        from .aggregate import avg_decimal_finalize
        sd, sv = eval_expr(expr.sum, batch)
        cd, cv = eval_expr(expr.count, batch)
        res = avg_decimal_finalize(sd, cd, xp=jnp)
        return res, sv & cv & (cd != 0)

    if isinstance(expr, ir.ExtractField):
        d, v = eval_expr(expr.arg, batch)
        is_ts = expr.arg.dtype.kind is TypeKind.TIMESTAMP
        micros_in_day = 86_400_000_000
        if expr.part.startswith('trunc_'):
            unit = expr.part[len('trunc_'):]
            if unit in ('hour', 'minute', 'second'):   # timestamp only
                step = {'hour': 3_600_000_000, 'minute': 60_000_000,
                        'second': 1_000_000}[unit]
                return d - d % step, v
            days = d // micros_in_day if is_ts else d
            if unit == 'day':
                out = days
            elif unit == 'week':
                # epoch day 0 = Thursday; Monday-based weeks (ISO)
                out = days - (days + 3) % 7
            else:
                year, month, _day = civil_from_days(days)
                if unit == 'month':
                    out = days_from_civil(year, month, 1)
                elif unit == 'quarter':
                    q_month = ((month - 1) // 3) * 3 + 1
                    out = days_from_civil(year, q_month, 1)
                else:                                  # year
                    out = days_from_civil(year, jnp.ones_like(month), 1)
            out = out.astype(d.dtype)
            return (out * micros_in_day if is_ts else out), v
        if is_ts:
            days = d // micros_in_day
            rem = d - days * micros_in_day
            if expr.part == 'hour':
                return rem // 3_600_000_000, v
            if expr.part == 'minute':
                return (rem // 60_000_000) % 60, v
            if expr.part == 'second':
                return (rem // 1_000_000) % 60, v
            d = days
        year, month, day = civil_from_days(d)
        res = {'year': year, 'month': month, 'day': day}[expr.part]
        return res.astype(jnp.int64), v

    if isinstance(expr, ir.DictValueMap):
        d, v = eval_expr(expr.arg, batch)
        lut = jnp.asarray(expr.values, dtype=expr.dtype.np_dtype)
        codes = jnp.clip(d.astype(jnp.int32), 0, len(expr.values) - 1)
        return lut[codes], v

    if isinstance(expr, ir.ScalarFunc):
        return eval_scalar_func(expr, batch)

    raise NotImplementedError(f"eval of {type(expr).__name__}")


def eval_scalar_func(expr: ir.ScalarFunc, batch: Batch):
    """Built-in scalar functions (reference: operator/scalar/ — MathFunctions,
    ConditionalFunctions), branch-free with three-valued logic."""
    name = expr.name
    parts = [eval_expr(a, batch) for a in expr.args]

    if name == "coalesce":
        d, v = parts[-1]
        d = d.astype(expr.dtype.np_dtype)
        for pd, pv in reversed(parts[:-1]):
            d = jnp.where(pv, pd.astype(expr.dtype.np_dtype), d)
            v = pv | v
        return d, v

    if name == "nullif":
        (ad, av), (bd, bv) = parts
        eq = av & bv & (ad == bd.astype(ad.dtype))
        return ad, av & ~eq

    if name in ("greatest", "least"):
        op = jnp.maximum if name == "greatest" else jnp.minimum
        d, v = parts[0]
        d = d.astype(expr.dtype.np_dtype)
        for pd, pv in parts[1:]:
            d = op(d, pd.astype(expr.dtype.np_dtype))
            v = v & pv          # NULL if any argument is NULL (Trino)
        return d, v

    (d, v) = parts[0]
    t = expr.args[0].dtype
    if name == "abs":
        return jnp.abs(d), v
    if name == "round":
        digits = expr.params[0] if expr.params else 0
        if t.kind is TypeKind.DECIMAL:
            # round at `digits` decimal places, keep the scale
            if digits >= t.scale:
                return d, v
            return rescale(rescale(d, t.scale, digits), digits, t.scale), v
        factor = jnp.float64(10.0 ** digits)
        xs = d.astype(jnp.float64) * factor
        half_up = jnp.where(xs >= 0, jnp.floor(xs + 0.5),
                            jnp.ceil(xs - 0.5))
        return half_up / factor, v
    if name in ("floor", "ceil"):
        if t.kind is TypeKind.DECIMAL:
            s = 10 ** t.scale
            # on scaled ints: floor -> toward -inf, ceil -> toward +inf
            q = d // s if name == "floor" else -((-d) // s)
            return q, v
        if jnp.issubdtype(d.dtype, jnp.floating):
            op = jnp.floor if name == "floor" else jnp.ceil
            return op(d), v
        return d.astype(jnp.int64), v
    if name == "mod":
        (rd, rv) = parts[1]
        r = rd.astype(d.dtype)
        safe = jnp.where(r == 0, jnp.ones_like(r), r)
        if jnp.issubdtype(d.dtype, jnp.floating):
            res = d - jnp.trunc(d / safe) * safe
        else:
            q = d // safe
            rem = d - q * safe
            # SQL mod truncates toward zero: sign follows the dividend
            res = jnp.where((rem != 0) & ((d < 0) != (r < 0)),
                            rem - safe, rem)
            res = jnp.where(rem == 0, rem, res)
        return res, v & parts[1][1] & (rd != 0)
    if name == "sqrt":
        x = d.astype(jnp.float64)
        return jnp.sqrt(jnp.abs(x)), v & (x >= 0)
    if name == "power":
        (rd, rv) = parts[1]
        return jnp.power(d.astype(jnp.float64),
                         rd.astype(jnp.float64)), v & rv
    if name == "exp":
        return jnp.exp(d.astype(jnp.float64)), v
    if name == "ln":
        x = d.astype(jnp.float64)
        return jnp.log(jnp.where(x > 0, x, jnp.float64(1))), v & (x > 0)

    # ---- two-limb decimal accumulation (sum over DECIMAL) ------------
    # The reference accumulates wide sums in Int128State
    # (spi/type/Int128.java); here the planner splits each unscaled
    # value into (hi = x >> 32, lo = x & 0xffffffff) so two ordinary
    # int64 segment sums carry the state exactly (lo is canonical
    # non-negative; sums of up to 2^31 rows cannot wrap), and the
    # post-agg combine hi*2^32 + lo is exact while |total| < 2^63.
    if name == "$limb_hi":
        x = d.astype(jnp.int64)
        return jax.lax.shift_right_arithmetic(x, 32), v
    if name == "$limb_lo":
        x = d.astype(jnp.int64)
        return jnp.bitwise_and(x, jnp.int64(0xFFFFFFFF)), v
    if name == "$limb_combine":
        # raw unscaled combine (NULL when either limb sum is NULL —
        # both are NULL together for empty/all-NULL groups)
        (lod, lov) = parts[1]
        hi = d.astype(jnp.int64)
        return (hi << 32) + lod.astype(jnp.int64), v & lov

    # ---- HyperLogLog building blocks (approx_distinct) ---------------
    # The reference keeps an HLL sketch object per group
    # (operator/aggregation/ApproximateCountDistinctAggregation.java +
    # airlift HyperLogLog). TPU redesign: the sketch IS a relational
    # rewrite — registers become (group, bucket) rows of an inner
    # max-aggregate, so partials merge through the ordinary mergeable-
    # aggregation machinery (chunked + distributed for free) with
    # bounded 2^p-per-group state. These scalars are the hash-side
    # primitives of that rewrite.
    if name in ("$hll_bucket", "$hll_rho"):
        p = expr.params[0]
        h = _hll_hash64(d)
        if name == "$hll_bucket":
            return jax.lax.shift_right_logical(h, 64 - p), v
        w = jax.lax.shift_left(h, p)
        rho = jnp.minimum(jax.lax.clz(w) + 1, 64 - p + 1)
        return rho.astype(jnp.int64), v
    if name == "$hll_pow":
        # 2^-rho contribution to the harmonic mean; NULL passes through
        return jnp.exp2(-d.astype(jnp.float64)), v
    if name == "$hll_est":
        # finisher over (V = occupied registers, S = sum 2^-rho):
        # raw HLL estimate with linear-counting correction for the
        # small range, 0 for all-NULL/empty groups
        m = float(expr.params[0])
        (vd, vv) = parts[0]
        (sd, sv) = parts[1]
        V = jnp.where(vv, vd, 0).astype(jnp.float64)
        S = jnp.where(sv, sd, 0.0).astype(jnp.float64)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        raw = alpha * m * m / (S + (m - V))
        zeros = m - V
        lin = m * jnp.log(jnp.where(zeros > 0, m / jnp.maximum(zeros, 0.5),
                                    1.0))
        est = jnp.where((raw <= 2.5 * m) & (zeros > 0), lin, raw)
        est = jnp.where(V == 0, 0.0, est)
        return jnp.round(est).astype(jnp.int64), jnp.ones_like(vv)
    raise NotImplementedError(f"scalar function {name}")


def _hll_hash64(d):
    """splitmix64 finalizer over the lane value (int64 two's-complement
    wraparound arithmetic; logical shifts via lax). Doubles hash their
    bit pattern; dictionary codes hash as ints (code identity == string
    identity within a pool)."""
    if jnp.issubdtype(d.dtype, jnp.floating):
        x = jax.lax.bitcast_convert_type(d.astype(jnp.float64), jnp.int64)
    else:
        x = d.astype(jnp.int64)
    x = x + jnp.int64(-7046029254386353131)          # 0x9E3779B97F4A7C15
    x = x ^ jax.lax.shift_right_logical(x, 30)
    x = x * jnp.int64(-4658895280553007687)          # 0xBF58476D1CE4E5B9
    x = x ^ jax.lax.shift_right_logical(x, 27)
    x = x * jnp.int64(-7723592293110705685)          # 0x94D049BB133111EB
    x = x ^ jax.lax.shift_right_logical(x, 31)
    return x


def filter_mask(expr: ir.Expr, batch: Batch) -> jax.Array:
    """WHERE semantics: NULL -> excluded."""
    d, v = eval_expr(expr, batch)
    return d & v


def apply_filter(batch: Batch, expr: ir.Expr) -> Batch:
    """Filter = AND into the live mask; no data movement (the TPU analog of
    Trino's SelectedPositions, operator/project/SelectedPositions.java)."""
    return batch.with_live(batch.live & filter_mask(expr, batch))


def project(batch: Batch, exprs) -> Batch:
    """Evaluate projection list into a new Batch (same capacity/live)."""
    cols = []
    for e in exprs:
        d, v = eval_expr(e, batch)
        cols.append(Column(data=d, valid=v))
    return Batch(columns=tuple(cols), live=batch.live)


@recorded_jit(static_argnums=(1, 2))
def filter_project(batch: Batch, filter_expr, project_exprs) -> Batch:
    """Jitted fused filter+project — the PageProcessor equivalent
    (operator/project/PageProcessor.java:99). Expressions are static
    (hashable IR), so each distinct plan compiles once and is cached."""
    b = apply_filter(batch, filter_expr) if filter_expr is not None else batch
    return project(b, project_exprs)
