"""Window function kernels — sort-based, scatter-free.

Reference: Trino's WindowOperator sorts each partition inside a PagesIndex
and drives per-function WindowFunction.processRow loops
(operator/WindowOperator.java:70, operator/window/). TPU redesign: ONE
multi-operand `lax.sort` by (partition keys, order keys) for the whole
batch, then every window function is a combination of

- segment boundaries (adjacent-difference on sorted key operands),
- running `cumsum` / segmented `associative_scan`,
- `searchsorted` gathers for partition/peer extents,

all static-shape and gather-only. Results return to the original row order
through the inverse permutation (itself computed by a second sort — no
scatter anywhere).

Frames supported (the planner maps SQL frames onto these):
- "partition":     the whole partition (no ORDER BY, or UNBOUNDED..UNBOUNDED)
- "range_running": RANGE UNBOUNDED PRECEDING..CURRENT ROW (default frame —
                   includes the full peer group of the current row)
- "rows_running":  ROWS UNBOUNDED PRECEDING..CURRENT ROW
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..exec.profiler import recorded_jit
from jax import lax

from ..batch import Batch, Column
from .sort import _sort_key_encoding

RANKING = ("row_number", "rank", "dense_rank", "ntile")
VALUE_FUNCS = ("lead", "lag", "first_value", "last_value")
AGG_FUNCS = ("sum", "count", "count_star", "min", "max")
FRAMES = ("partition", "range_running", "rows_running")


@dataclass(frozen=True)
class WinSpec:
    """One window function over the shared (partition, order) sort."""
    func: str                       # RANKING | VALUE_FUNCS | AGG_FUNCS
    arg_index: Optional[int] = None  # input column (None: row_number etc.)
    frame: str = "partition"        # FRAMES (aggregates/last_value only)
    offset: int = 1                 # lead/lag offset, ntile bucket count
    default: Optional[object] = None  # lead/lag default literal

    def __post_init__(self):
        assert self.func in RANKING + VALUE_FUNCS + AGG_FUNCS, self.func
        assert self.frame in FRAMES or \
            self.frame.startswith(("rows_bounded:",
                                   "range_bounded:")), self.frame


def _scan_max(vals: jax.Array) -> jax.Array:
    """Running maximum (propagates the latest boundary index forward)."""
    return lax.associative_scan(jnp.maximum, vals)


def _lower_bound(vals: jax.Array, lo0: jax.Array, hi0: jax.Array,
                 target: jax.Array) -> jax.Array:
    """Per-row vectorized binary search: first j in [lo0, hi0] with
    vals[j] >= target (vals non-decreasing on that range). 31 unrolled
    halvings cover any capacity < 2^31 — the RANGE-frame boundary
    finder (the role OrderingCompiler-built comparators play in
    WindowOperator's frame addressing)."""
    import math
    n = vals.shape[0]
    lo, hi = lo0, hi0 + 1
    for _ in range(max(1, math.ceil(math.log2(n + 1)))):
        cont = lo < hi
        mid = (lo + hi) >> 1
        less = vals[jnp.clip(mid, 0, n - 1)] < target
        lo = jnp.where(cont & less, mid + 1, lo)
        hi = jnp.where(cont & ~less, mid, hi)
    return lo


@recorded_jit(static_argnums=(1, 2, 3))
def window_compute(batch: Batch, partition_keys: tuple, order_keys: tuple,
                   specs: tuple) -> Batch:
    """Append one column per spec, in the batch's ORIGINAL row order.

    partition_keys: tuple[int] — column indices; NULLs form one partition
    (SQL: PARTITION BY treats NULLs as equal, like GROUP BY).
    order_keys: tuple[(col_index, ascending, nulls_first)].
    """
    n = batch.capacity
    idx = jnp.arange(n, dtype=jnp.int32)

    operands = [(~batch.live).astype(jnp.int8)]   # dead rows sort last
    for ki in partition_keys:
        col = batch.columns[ki]
        operands.append((~col.valid).astype(jnp.int8))
        # NULL keys form one partition: normalize masked data (see
        # sort_group_aggregate)
        operands.append(jnp.where(col.valid, col.data,
                                  jnp.zeros((), col.data.dtype)))
    n_part_ops = len(operands)
    for (ki, asc, nf) in order_keys:
        nr, data = _sort_key_encoding(batch.columns[ki], asc, nf)
        operands.append(nr)
        operands.append(data)
    num_keys = len(operands)
    operands.append(idx)                          # payload: original row
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    live_s = batch.live[perm]

    # inverse permutation via a second sort (gather-only scatter avoidance)
    inv_ops = jax.lax.sort((perm, idx), num_keys=1)
    invperm = inv_ops[-1]

    first = idx == 0
    part_diff = jnp.zeros(n, dtype=jnp.bool_)
    for op in sorted_ops[1:n_part_ops]:
        part_diff = part_diff | (op != jnp.roll(op, 1))
    part_boundary = live_s & (first | part_diff)
    order_diff = part_diff
    for op in sorted_ops[n_part_ops:num_keys]:
        order_diff = order_diff | (op != jnp.roll(op, 1))
    peer_boundary = live_s & (first | order_diff)

    big = jnp.int32(n + 1)
    seg = jnp.cumsum(part_boundary.astype(jnp.int32)) - 1
    seg = jnp.where(live_s, seg, big)             # dead rows: own segment
    pid = jnp.cumsum(peer_boundary.astype(jnp.int32)) - 1
    pid = jnp.where(live_s, pid, big)

    part_start = jnp.searchsorted(seg, seg, side="left").astype(jnp.int32)
    part_end = (jnp.searchsorted(seg, seg, side="right") - 1).astype(
        jnp.int32)
    peer_end = (jnp.searchsorted(pid, pid, side="right") - 1).astype(
        jnp.int32)
    part_start = jnp.clip(part_start, 0, n - 1)
    part_end = jnp.clip(part_end, 0, n - 1)
    peer_end = jnp.clip(peer_end, 0, n - 1)

    row0 = idx - part_start                       # 0-based position
    peer_cum = jnp.cumsum(peer_boundary.astype(jnp.int64))
    dense = peer_cum - peer_cum[part_start] + 1   # dense_rank

    def frame_end(frame: str) -> jax.Array:
        if frame == "partition":
            return part_end
        if frame == "range_running":
            return peer_end
        return idx                                # rows_running

    out_cols = list(batch.columns)
    for spec in specs:
        f = spec.func
        if f == "row_number":
            data = (row0 + 1).astype(jnp.int64)
            col = Column(data[invperm], batch.live)
        elif f == "rank":
            # rank = index of the peer group's first row within partition
            peer_start = _scan_max(jnp.where(peer_boundary, idx, -1))
            data = (peer_start - part_start + 1).astype(jnp.int64)
            col = Column(data[invperm], batch.live)
        elif f == "dense_rank":
            col = Column(dense.astype(jnp.int64)[invperm], batch.live)
        elif f == "ntile":
            k = spec.offset
            size = part_end - part_start + 1
            base, rem = size // k, size % k
            fat = base + 1                        # first `rem` tiles
            in_fat = row0 < fat * rem
            tile = jnp.where(
                in_fat,
                row0 // jnp.maximum(fat, 1),
                rem + (row0 - fat * rem) // jnp.maximum(base, 1))
            col = Column((tile + 1).astype(jnp.int64)[invperm], batch.live)
        elif f in ("lead", "lag"):
            src = batch.columns[spec.arg_index]
            data_s, valid_s = src.data[perm], src.valid[perm]
            off = spec.offset if f == "lead" else -spec.offset
            tgt = idx + off
            in_part = (tgt >= part_start) & (tgt <= part_end)
            tgt = jnp.clip(tgt, 0, n - 1)
            if spec.default is None:
                dval = jnp.zeros((), dtype=src.data.dtype)
                dvalid = jnp.zeros((), dtype=jnp.bool_)
            else:
                dval = jnp.asarray(spec.default, dtype=src.data.dtype)
                dvalid = jnp.ones((), dtype=jnp.bool_)
            data = jnp.where(in_part, data_s[tgt], dval)
            valid = jnp.where(in_part, valid_s[tgt], dvalid) & live_s
            col = Column(data[invperm], valid[invperm] & batch.live)
        elif f == "first_value":
            src = batch.columns[spec.arg_index]
            data_s, valid_s = src.data[perm], src.valid[perm]
            col = Column(data_s[part_start][invperm],
                         (valid_s[part_start])[invperm] & batch.live)
        elif f == "last_value":
            src = batch.columns[spec.arg_index]
            data_s, valid_s = src.data[perm], src.valid[perm]
            end = frame_end(spec.frame)
            col = Column(data_s[end][invperm],
                         (valid_s[end])[invperm] & batch.live)
        else:                                     # framed aggregates
            if spec.frame.startswith("rows_bounded:"):
                _, p_s, f_s = spec.frame.split(":")
                fstart = jnp.maximum(part_start, idx - int(p_s))
                end = jnp.minimum(part_end, idx + int(f_s))
                empty = end < fstart
                end = jnp.clip(end, 0, n - 1)
            elif spec.frame.startswith("range_bounded:"):
                # RANGE x PRECEDING .. y FOLLOWING: frame bounds are
                # VALUE offsets over the single ORDER BY key. Rows are
                # already sorted by (partition, key), so the bounds are
                # per-partition binary searches over the sorted values;
                # NULL-key rows frame their peer group (SQL: NULL is its
                # own peer class in RANGE mode).
                _, p_s, f_s = spec.frame.split(":")
                prec, foll = int(p_s), int(f_s)
                unbounded_prec = prec >= (1 << 62)
                ki, asc, nf = order_keys[0]
                okey = batch.columns[ki]
                ovalid_s = okey.valid[perm]
                imax = jnp.int64(jnp.iinfo(jnp.int64).max)
                ov = okey.data[perm].astype(jnp.int64)
                ov = ov if asc else -ov
                # NULL keys sit in one block at the partition edge; a
                # sentinel on the sorted side keeps v monotone so the
                # searches never land inside the block. Bound arithmetic
                # SATURATES so 63-bit key values can't wrap past it.
                v = jnp.where(ovalid_s, ov, -imax if nf else imax)
                if unbounded_prec:
                    # frame starts at the partition's first row,
                    # INCLUDING a leading NULL block (SQL semantics)
                    lo_t = jnp.full_like(v, -imax)
                else:
                    lo_t = jnp.where(v < -imax + prec, -imax, v - prec)
                hi_t = jnp.where(v > imax - 1 - foll, imax - 1, v + foll)
                fstart = _lower_bound(v, part_start, idx, lo_t)
                end = _lower_bound(v, idx, part_end, hi_t + 1) - 1
                peer_start = _scan_max(
                    jnp.where(peer_boundary, idx, -1))
                # NULL rows: frame = their peer block — except with an
                # UNBOUNDED PRECEDING start, which reaches back to the
                # partition's first row regardless of NULL placement
                null_start = part_start if unbounded_prec else peer_start
                fstart = jnp.where(ovalid_s, fstart, null_start)
                end = jnp.where(ovalid_s, end, peer_end)
                empty = end < fstart
                fstart = jnp.clip(fstart, 0, n - 1)
                end = jnp.clip(end, 0, n - 1)
            else:
                fstart = part_start
                end = frame_end(spec.frame)
                empty = jnp.zeros(n, dtype=jnp.bool_)
            before = jnp.where(fstart > 0,
                               jnp.clip(fstart - 1, 0, n - 1), 0)

            def running_total(vals):
                cs = jnp.cumsum(vals)
                lo = jnp.where(fstart > 0, cs[before], 0)
                return jnp.where(empty, 0, cs[end] - lo)

            if f == "count_star":
                data = running_total(live_s.astype(jnp.int64))
                col = Column(data[invperm], batch.live)
            else:
                src = batch.columns[spec.arg_index]
                data_s = src.data[perm]
                valid_s = src.valid[perm] & live_s
                cnt = running_total(valid_s.astype(jnp.int64))
                if f == "count":
                    col = Column(cnt[invperm], batch.live)
                elif f == "sum":
                    acc = jnp.int64 if jnp.issubdtype(
                        src.data.dtype, jnp.integer) else src.data.dtype
                    vals = jnp.where(valid_s, data_s.astype(acc), 0)
                    data = running_total(vals)
                    col = Column(data[invperm],
                                 (cnt > 0)[invperm] & batch.live)
                else:                             # min / max
                    if jnp.issubdtype(data_s.dtype, jnp.floating):
                        ident = jnp.inf if f == "min" else -jnp.inf
                    else:
                        info = jnp.iinfo(data_s.dtype)
                        ident = info.max if f == "min" else info.min
                    op = jnp.minimum if f == "min" else jnp.maximum
                    vals = jnp.where(valid_s, data_s, ident)

                    def combine(a, b):
                        fa, va = a
                        fb, vb = b
                        return fa | fb, jnp.where(fb, vb, op(va, vb))
                    _, scanned = lax.associative_scan(
                        combine, (part_boundary, vals))
                    data = scanned[end]
                    col = Column(data[invperm],
                                 (cnt > 0)[invperm] & batch.live)
        out_cols.append(col)
    return Batch(columns=tuple(out_cols), live=batch.live)
