"""Pallas MXU group-aggregation kernel — one HBM pass for grouped sums.

The direct (dense small-domain) aggregation strategy in XLA form
(ops/aggregate.py) evaluates G x A masked reductions; XLA fuses them into a
few passes over the batch. This kernel does the whole thing in ONE pass by
turning grouping into a matmul on the systolic array (the canonical
scatter-free TPU trick):

    partial[g, c] = onehot[g, :] @ parts[:, c]

- int64 values ride as two int32 planes (hi/lo), since Mosaic has no i64
  reductions and the axon AOT path cannot rewrite s64 custom-call operands;
- each value is split in-kernel into five 12-bit limbs plus a negative-count
  column, all exactly representable in f32; the one-hot matmul with
  Precision.HIGHEST (bf16x3) then accumulates them exactly (every partial
  sum stays below 2^24);
- per-block partials [n_blocks, SUB, G, C] are combined in XLA as int64:
  sum_g v = sum_limbs(limb_sum << 12k) - (neg_count << 60).

Exact for |value| < 2^59 — any SUM whose inputs exceed that is at overflow
risk in int64 regardless (Trino short decimals stop at 2^63 too).

Reference role: compiled accumulators + GroupByHash's dense mode
(operator/aggregation/AccumulatorCompiler.java:88, BigintGroupByHash).

Measured (v5e, TPC-H SF1 q1 shape, G=6, A=6): 7.4ms vs 2.1ms for the XLA
masked-reduction path — the custom-call boundary forces the hi/lo planes to
materialize in HBM, which costs more than the fused single-pass XLA graph
saves at small G; the win region is larger group counts, where the XLA
path's unrolled G x A reduction graph grows linearly while this stays one
matmul pass. The strategy gate therefore picks the kernel as the LARGE end
of the direct-domain arm: `mxu_agg` = auto (default) routes direct
aggregates with G >= Executor.MXU_AGG_MIN_GROUPS here on TPU backends and
keeps the fused XLA graph below it; true/false force either way. (Round-12
folded the kernel into the gate — it previously idled behind an opt-in
nobody turned on.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..exec.profiler import recorded_jit
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..batch import Batch, Column
from .aggregate import AggSpec

BLK = 2048          # lane-dim elements per sublane row (VMEM-sized)
SUB = 8             # sublane rows per grid step
BLOCK_ELEMS = BLK * SUB
LIMBS = 5           # 12-bit limbs -> 60 bits
COLS_PER_AGG = LIMBS + 1              # + negative-count column

# VMEM budget guard: onehot [SUB,G,BLK] + parts [SUB,C,BLK] f32
MAX_GROUPS = 16
MAX_AGGS = 8


def supports(aggs, domains) -> bool:
    g = int(np.prod(domains)) if domains else 0
    if not (0 < g <= MAX_GROUPS and len(aggs) <= MAX_AGGS):
        return False
    return all(a.func in ("sum", "count", "count_star") and not a.distinct
               for a in aggs)


def _kernel(n_groups: int, n_cols: int, n_aggs: int):
    def kernel(gid_ref, hi_ref, lo_ref, out_ref):
        gid = gid_ref[0]                                       # [SUB,BLK]
        onehot = jnp.stack(
            [(gid == g).astype(jnp.float32) for g in range(n_groups)],
            axis=1)                                            # [SUB,G,BLK]
        cols = []
        for a in range(n_aggs):
            hi, lo = hi_ref[a], lo_ref[a]
            cols.append((lo & 0xFFF).astype(jnp.float32))
            cols.append(((lo >> 12) & 0xFFF).astype(jnp.float32))
            cols.append(((((lo >> 24) & 0xFF) +
                          ((hi & 0xF) * 256))).astype(jnp.float32))
            cols.append(((hi >> 4) & 0xFFF).astype(jnp.float32))
            cols.append(((hi >> 16) & 0xFFF).astype(jnp.float32))
            cols.append(((hi >> 31) & 1).astype(jnp.float32))
        while len(cols) < n_cols:
            cols.append(jnp.zeros_like(cols[0]))
        parts = jnp.stack(cols, axis=1)                        # [SUB,C,BLK]
        r = jax.lax.dot_general(
            onehot, parts, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)               # [SUB,G,C]
        out_ref[...] = r[None]
    return kernel


@recorded_jit(static_argnums=(3, 4))
def _mxu_sums(gid: jax.Array, hi: jax.Array, lo: jax.Array,
              n_groups: int, interpret: bool) -> jax.Array:
    """gid [n] int32 (n_groups = miss), hi/lo [A, n] int32 ->
    int64 totals [n_groups, A_cols] where A_cols = hi.shape[0]."""
    n_aggs, n = hi.shape
    n_cols = ((n_aggs * COLS_PER_AGG + 7) // 8) * 8
    nb = n // BLOCK_ELEMS
    with jax.enable_x64(False):
        out = pl.pallas_call(
            _kernel(n_groups, n_cols, n_aggs),
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((1, SUB, BLK), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n_aggs, SUB, BLK), lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n_aggs, SUB, BLK), lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((1, SUB, n_groups, n_cols),
                                   lambda i: (i, 0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((nb, SUB, n_groups, n_cols),
                                           jnp.float32),
            interpret=interpret,
        )(gid.reshape(nb, SUB, BLK), hi.reshape(n_aggs, nb * SUB, BLK),
          lo.reshape(n_aggs, nb * SUB, BLK))
    acc = out.astype(jnp.int64).sum(axis=(0, 1))         # [G, n_cols]
    tot = jnp.zeros((n_groups, n_aggs), dtype=jnp.int64)
    for a in range(n_aggs):
        base = a * COLS_PER_AGG
        col = jnp.zeros((n_groups,), dtype=jnp.int64)
        for p in range(LIMBS):
            col = col + (acc[:, base + p] << (12 * p))
        col = col - (acc[:, base + LIMBS] << 60)
        tot = tot.at[:, a].set(col)
    return tot


@recorded_jit(static_argnums=(1, 2, 3, 4))
def direct_group_aggregate_mxu(batch: Batch, key_indices: tuple,
                               domains: tuple, aggs: tuple,
                               interpret: bool = False) -> Batch:
    """Drop-in for ops.aggregate.direct_group_aggregate when supports()
    holds: same output layout (key digit columns, then aggregate states)."""
    n_groups = 1
    for d in domains:
        n_groups *= d

    cap = batch.capacity
    pad = (-cap) % BLOCK_ELEMS
    n = cap + pad

    gid = jnp.zeros(cap, dtype=jnp.int32)
    key_valid = jnp.ones(cap, dtype=jnp.bool_)
    for ki, d in zip(key_indices, domains):
        col = batch.columns[ki]
        gid = gid * d + jnp.clip(col.data.astype(jnp.int32), 0, d - 1)
        key_valid = key_valid & col.valid
    contributes = batch.live & key_valid
    gid = jnp.where(contributes, gid, n_groups)     # miss group
    gid = jnp.pad(gid, (0, pad), constant_values=n_groups)

    # value planes: one per aggregate + a leading live-count plane
    planes = [jnp.where(contributes, 1, 0).astype(jnp.int64)]
    for spec in aggs:
        if spec.func == "count_star":
            planes.append(planes[0])
        else:
            col = batch.columns[spec.arg_index]
            m = contributes & col.valid
            if spec.func == "count":
                planes.append(jnp.where(m, 1, 0).astype(jnp.int64))
            else:
                planes.append(jnp.where(m, col.data.astype(jnp.int64), 0))
        # validity companion: non-null contributor count per group
        if spec.func == "sum":
            col = batch.columns[spec.arg_index]
            planes.append(jnp.where(contributes & col.valid, 1, 0)
                          .astype(jnp.int64))
    v = jnp.stack([jnp.pad(p, (0, pad)) for p in planes])
    hi = (v >> 32).astype(jnp.int32)
    lo = (v & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)

    tot = _mxu_sums(gid, hi, lo, n_groups, interpret)  # [G, planes]

    group_count = tot[:, 0]
    group_live = group_count > 0
    out_cols = []
    g_idx = jnp.arange(n_groups, dtype=jnp.int32)
    radix = n_groups
    for ki, d in zip(key_indices, domains):
        radix //= d
        digit = (g_idx // radix) % d
        out_cols.append(Column(
            data=digit.astype(batch.columns[ki].data.dtype),
            valid=group_live))
    plane = 1
    for spec in aggs:
        state = tot[:, plane]
        plane += 1
        if spec.func in ("count", "count_star"):
            out_cols.append(Column(data=state, valid=group_live))
        else:                                   # sum + its validity plane
            cnt = tot[:, plane]
            plane += 1
            out_cols.append(Column(data=state,
                                   valid=group_live & (cnt > 0)))
    return Batch(columns=tuple(out_cols), live=group_live)
