"""Pallas VMEM-resident hash-table kernel — hash aggregation + join build.

Every heavy grouping path so far is sort-based (ops/aggregate.py) or
dense-LUT (ops/join.py): q18's 1.5M-group aggregate pays a full
lexicographic `lax.sort` because its key domain is sparse.  The
hash-based alternative the literature keeps landing on ("Global Hash
Tables Strike Back!", "Hash-Based vs. Sort-Based Group-By-Aggregate" —
PAPERS.md) needs data-dependent insertion, which XLA TPU can only
express as serialized scatters (~80 ns/row PER scatter op, one per
aggregate).  This kernel does the whole insert-or-accumulate in ONE
pass over the input with the table resident in VMEM:

- **one global table, sequential grid**: TPU grid steps run in order on
  a core, so the table planes are an output block REVISITED by every
  step (the accumulator pattern of `pallas_gather._scan_kernel`) — a
  shared global hash table with zero races, exactly the structure the
  GPU literature reaches with atomics.
- **open addressing, linear probing**: slot = splitmix64(key + SEED) %
  T (computed in XLA — the kernel has no 64-bit multiplier), probe
  bound MAX_PROBES, occupancy capped at LOAD_NUM/LOAD_DEN of T.  A row
  that exhausts its probes or would breach the load cap is COUNTED as
  an escape; the caller must discard the run and radix-partition the
  batch with the spill tier's splitmix64 partitioner
  (`exec/spill._partition_ids`) so each partition re-enters the kernel
  — the same partitions the round-9 host-spill tier uses, so memory
  pressure composes bit-exactly. SEED decorrelates the slot hash from
  the partitioner (both are splitmix64; without a distinct seed a
  power-of-two partition count would leave only T/P reachable slots
  per partition).
- **int32 bit-planes for 64-bit lanes**: Mosaic has no i64, so keys and
  sum states ride (lo, hi) int32 plane pairs (the `pallas_gather.py`
  trick).  64-bit accumulation is exact two's-complement limb
  arithmetic: lo adds with an unsigned-compare carry into hi, so hash
  sums match the XLA int64 sort-path sums bit for bit, wrap included.
- **insert-or-accumulate is scalar-core work**: the per-row body is a
  probe `while_loop` plus a handful of scalar VMEM reads/writes per
  aggregate.  That is the honest TPU cost model for data-dependent
  writes (~tens of ns/row on the scalar core) — orders of magnitude
  under the sort path's O(n log n) at high cardinality, and ONE pass
  over HBM instead of the sort's several.

Aggregation contract (`hash_group_aggregate`): integer-typed keys
packed into ONE int64 word by the executor's range-compression plan
(`ops.aggregate.key_pack_plan` — lossless, so equality is exact; no
hash-collision risk ever reaches results), integer-typed aggregate
arguments, funcs sum/count/count_star/min/max, no DISTINCT (the
strategy gate routes DISTINCT to the sort kernel).  Output is a batch
of capacity `table_slots` whose live mask marks occupied slots: key
columns decode from the packed word (digit 0 = NULL, NULLs group
together), aggregate states are bit-exact vs `sort_group_aggregate`.
Group order is slot order — no operator here guarantees row order.

Join build (`build_join_table`): the SAME kernel with the aggregate
layout (min(row_id), count(*)) — the build side of a hash join IS a
hash aggregation of row ids by key.  Duplicate build keys show up as
inserted_rows > occupied_slots (one fused validation fetch, like the
dense LUT's dup check); probing (`hash_join_probe`) walks the linear
chain with MAX_PROBES rounds of `pallas_gather`-fused multi-plane
gathers.  Because insertion never displaces beyond MAX_PROBES (that is
an escape), a probe that sees MAX_PROBES non-empty non-matching slots
is a DEFINITIVE miss — no escape path exists on the probe side.

Session wiring: `enable_pallas_hash` = auto (on for TPU) | true (TPU:
compiled; CPU: interpret mode — tier-1 runs the kernel through the
Pallas interpreter) | false.  Every site keeps its sort-path fallback.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..batch import Batch, Column
from ..exec.profiler import recorded_jit
from .aggregate import AggSpec

SUB = 8                      # sublane rows per input block
LANES = 128                  # lanes per row
BLOCK = SUB * LANES          # rows inserted per grid step
MAX_PROBES = 16              # linear-probe bound (breach = escape)
LOAD_NUM, LOAD_DEN = 5, 8    # occupancy cap 0.625 * T keeps probes short
# table sizes are powers of two in [MIN, MAX] slots; the per-call VMEM
# budget (key planes + state planes) additionally caps the choice
MIN_TABLE_SLOTS = 1 << 10
MAX_TABLE_SLOTS = 1 << 17
VMEM_TABLE_BYTES = 8 << 20
MAX_HASH_AGGS = 8

# empty-slot sentinel: the int64 pattern (hi=INT32_MIN, lo=0) == i64 min.
# Packed aggregation keys are always >= 0; join keys that equal i64 min
# (never a real key) are force-escaped in the wrapper, not inserted.
_EMPTY_HI = -(1 << 31)
_EMPTY_LO = 0
EMPTY_KEY = -(1 << 63)
_I32MIN = -(1 << 31)          # python int: jnp constants would be
                              # captured by the kernel closure

# slot-hash seed: decorrelates the in-table slot from the radix
# partitioner's splitmix64 (server/tasks.partition_assignment mixes
# key + column_position; this constant collides with neither)
_SLOT_SEED = np.uint64(0xD1B54A32D192ED03)

# aggregate kinds in the kernel's static layout
_K_COUNT, _K_SUM, _K_MIN, _K_MAX = 0, 1, 2, 3
_KIND = {"count": _K_COUNT, "count_star": _K_COUNT, "sum": _K_SUM,
         "min": _K_MIN, "max": _K_MAX}


def resolve_mode(setting) -> str:
    """Session-property value -> kernel mode ('device' | 'interpret' |
    'off') — same contract as pallas_gather.resolve_mode."""
    s = str(setting).lower()
    on_tpu = jax.default_backend() == "tpu"
    if s in ("true", "1"):
        return "device" if on_tpu else "interpret"
    if s == "auto":
        return "device" if on_tpu else "off"
    return "off"


def _splitmix64(x: jax.Array) -> jax.Array:
    """uint64 -> uint64 avalanche (the partitioner's mix, jnp form)."""
    z = x + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def hash_slot(key: jax.Array, table_slots: int) -> jax.Array:
    """Home slot per int64 key (computed in XLA; the kernel only walks
    the probe chain from here)."""
    h = _splitmix64(key.astype(jnp.int64).view(jnp.uint64) + _SLOT_SEED)
    return (h % jnp.uint64(table_slots)).astype(jnp.int32)


def agg_layout(aggs: tuple):
    """Static kernel layout: per-agg (kind, lo, hi, cnt, vlo, vhi) plane
    indices (-1 = unused) plus (state_planes, value_planes) totals."""
    layout = []
    ns = nv = 0
    for spec in aggs:
        kind = _KIND[spec.func]
        if kind == _K_COUNT:
            layout.append((kind, -1, -1, ns, -1, -1))
            ns += 1
        else:
            layout.append((kind, ns, ns + 1, ns + 2, nv, nv + 1))
            ns += 3
            nv += 2
    return tuple(layout), ns, max(nv, 1)


def max_table_slots(aggs: tuple) -> int:
    """Largest power-of-two table the VMEM budget allows for this
    aggregate layout (2 key planes + state planes, 4 B each)."""
    _, ns, _ = agg_layout(aggs)
    cap = VMEM_TABLE_BYTES // (4 * (2 + ns))
    t = MIN_TABLE_SLOTS
    while t * 2 <= min(cap, MAX_TABLE_SLOTS):
        t *= 2
    return t


def pick_table_slots(est_groups: int, aggs: tuple) -> Tuple[int, bool]:
    """(table_slots, fits): the smallest table whose load cap covers
    `est_groups`; fits=False means even the largest table cannot and
    the caller should radix-partition upfront."""
    cap = max_table_slots(aggs)
    t = MIN_TABLE_SLOTS
    while t * LOAD_NUM // LOAD_DEN < est_groups and t < cap:
        t *= 2
    return t, t * LOAD_NUM // LOAD_DEN >= est_groups


# --------------------------------------------------------------------------
# the insert-or-accumulate kernel
# --------------------------------------------------------------------------

def _u32_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """Unsigned 32-bit compare of int32 bit patterns."""
    return (a ^ _I32MIN) < (b ^ _I32MIN)


def _insert_kernel(layout: tuple, table_slots: int):
    t_rows = table_slots // LANES
    load_cap = table_slots * LOAD_NUM // LOAD_DEN

    def kernel(slot_ref, klo_ref, khi_ref, vb_ref, val_ref,
               tk_lo, tk_hi, st_ref, sc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            tk_lo[...] = jnp.full((t_rows, LANES), _EMPTY_LO, jnp.int32)
            tk_hi[...] = jnp.full((t_rows, LANES), _EMPTY_HI, jnp.int32)
            st_ref[...] = jnp.zeros(st_ref.shape, jnp.int32)
            sc_ref[0, 0] = jnp.int32(0)
            sc_ref[0, 1] = jnp.int32(0)

        def row(j, carry):
            esc, occ = carry
            r = j // LANES
            l = j % LANES
            slot = slot_ref[r, l]
            alive = slot >= 0
            klo = klo_ref[r, l]
            khi = khi_ref[r, l]

            def probe_cond(c):
                return c[2] == 0

            def probe_body(c):
                s, p, _ = c
                sr = s // LANES
                sl = s % LANES
                thi = tk_hi[sr, sl]
                tlo = tk_lo[sr, sl]
                empty = (thi == _EMPTY_HI) & (tlo == _EMPTY_LO)
                match = (~empty) & (thi == khi) & (tlo == klo)
                out = jnp.where(match, 1,
                                jnp.where(empty, 2, 0)).astype(jnp.int32)
                p2 = p + jnp.int32(1)
                out = jnp.where((out == 0) & (p2 >= MAX_PROBES),
                                jnp.int32(3), out)
                nxt = jnp.where(s + 1 >= table_slots, 0,
                                s + 1).astype(jnp.int32)
                return (jnp.where(out == 0, nxt, s), p2, out)

            s_f, _, outcome = jax.lax.while_loop(
                probe_cond, probe_body,
                (jnp.where(alive, slot, 0), jnp.int32(0), jnp.int32(0)))
            claim = alive & (outcome == 2) & (occ < load_cap)
            ok = (alive & (outcome == 1)) | claim
            esc = esc + jnp.where(alive & ~ok, 1, 0).astype(jnp.int32)
            occ = occ + jnp.where(claim, 1, 0).astype(jnp.int32)
            sr = s_f // LANES
            sl = s_f % LANES

            @pl.when(claim)
            def _():
                tk_lo[sr, sl] = klo
                tk_hi[sr, sl] = khi

            @pl.when(ok)
            def _():
                vb = vb_ref[r, l]
                for a, (kind, lo_p, hi_p, cnt_p, vlo_p,
                        vhi_p) in enumerate(layout):
                    bit = (vb >> a) & 1
                    cnt = st_ref[cnt_p, sr, sl]
                    if kind == _K_SUM:
                        alo = st_ref[lo_p, sr, sl]
                        ahi = st_ref[hi_p, sr, sl]
                        blo = val_ref[vlo_p, r, l]
                        bhi = val_ref[vhi_p, r, l]
                        slo = alo + blo
                        # exact i64 limb add: carry via unsigned compare
                        co = _u32_lt(slo, blo).astype(jnp.int32)
                        st_ref[lo_p, sr, sl] = slo
                        st_ref[hi_p, sr, sl] = ahi + bhi + co
                    elif kind in (_K_MIN, _K_MAX):
                        alo = st_ref[lo_p, sr, sl]
                        ahi = st_ref[hi_p, sr, sl]
                        blo = val_ref[vlo_p, r, l]
                        bhi = val_ref[vhi_p, r, l]
                        less = (bhi < ahi) | ((bhi == ahi) &
                                              _u32_lt(blo, alo))
                        better = less if kind == _K_MIN else \
                            (bhi > ahi) | ((bhi == ahi) &
                                           _u32_lt(alo, blo))
                        take = (bit == 1) & ((cnt == 0) | better)
                        st_ref[lo_p, sr, sl] = jnp.where(take, blo, alo)
                        st_ref[hi_p, sr, sl] = jnp.where(take, bhi, ahi)
                    st_ref[cnt_p, sr, sl] = cnt + bit
            return esc, occ

        esc0 = sc_ref[0, 0]
        occ0 = sc_ref[0, 1]
        esc, occ = jax.lax.fori_loop(0, BLOCK, row,
                                     (esc0, occ0))
        sc_ref[0, 0] = esc
        sc_ref[0, 1] = occ
    return kernel


def _pad_rows(x: jax.Array, fill) -> jax.Array:
    pad = (-x.shape[-1]) % BLOCK
    if pad == 0:
        return x
    width = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, width, constant_values=fill)


def _hash_insert(slot: jax.Array, klo: jax.Array, khi: jax.Array,
                 vbits: jax.Array, vals: jax.Array, layout: tuple,
                 table_slots: int, interpret: bool):
    """Run the insert-or-accumulate kernel. slot/klo/khi/vbits are
    [n] int32 (slot -1 = skip row), vals [NV, n] int32 value planes.
    Returns (tk_lo, tk_hi [T], states [NS, T], esc, occ int32)."""
    _, ns, nv = agg_layout_from(layout)
    n = slot.shape[0]
    slot = _pad_rows(slot, -1)
    klo = _pad_rows(klo, 0)
    khi = _pad_rows(khi, 0)
    vbits = _pad_rows(vbits, 0)
    vals = _pad_rows(vals, 0)
    npad = slot.shape[0]
    nb = npad // BLOCK
    t_rows = table_slots // LANES
    outs = pl.pallas_call(
        _insert_kernel(layout, table_slots),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((SUB, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUB, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUB, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUB, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nv, SUB, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((t_rows, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t_rows, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ns, t_rows, LANES), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0),
                         memory_space=pltpu.SMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((t_rows, LANES), jnp.int32),
            jax.ShapeDtypeStruct((t_rows, LANES), jnp.int32),
            jax.ShapeDtypeStruct((ns, t_rows, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, 2), jnp.int32)],
        interpret=interpret,
    )(slot.reshape(nb * SUB, LANES), klo.reshape(nb * SUB, LANES),
      khi.reshape(nb * SUB, LANES), vbits.reshape(nb * SUB, LANES),
      vals.reshape(nv, nb * SUB, LANES))
    tk_lo, tk_hi, st, sc = outs
    return (tk_lo.reshape(table_slots), tk_hi.reshape(table_slots),
            st.reshape(st.shape[0], table_slots), sc[0, 0], sc[0, 1])


def agg_layout_from(layout: tuple):
    """(layout, state_planes, value_planes) totals from a built layout
    (shared by _hash_insert so callers can't disagree with it)."""
    ns = nv = 0
    for kind, lo_p, hi_p, cnt_p, vlo_p, vhi_p in layout:
        ns = max(ns, cnt_p + 1, hi_p + 1)
        nv = max(nv, vhi_p + 1)
    return layout, ns, max(nv, 1)


def _split64(v: jax.Array):
    """int64 -> (lo, hi) int32 planes."""
    lo = (v & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)
    hi = (v >> 32).astype(jnp.int32)
    return lo, hi


def _join64(lo: jax.Array, hi: jax.Array) -> jax.Array:
    return (hi.astype(jnp.int64) << 32) | \
        (lo.astype(jnp.int64) & 0xFFFFFFFF)


# --------------------------------------------------------------------------
# hash aggregation over a packed key word
# --------------------------------------------------------------------------

def supports_aggs(batch: Batch, aggs: tuple) -> bool:
    """Hash-agg eligibility for the value side: no DISTINCT (routed to
    sort), <= MAX_HASH_AGGS aggregates, integer-typed arguments only
    (float sums are order-dependent; the sort path is the oracle)."""
    if len(aggs) > MAX_HASH_AGGS:
        return False
    for a in aggs:
        if a.distinct or a.func not in _KIND:
            return False
        if a.arg_index is not None:
            dt = batch.columns[a.arg_index].data.dtype
            if not (jnp.issubdtype(dt, jnp.integer) or
                    dt == jnp.bool_):
                return False
    return True


@recorded_jit(static_argnums=(2, 3, 4, 5, 6))
def hash_group_aggregate(batch: Batch, kmins, key_indices: tuple,
                         key_bits: tuple, aggs: tuple,
                         table_slots: int, mode: str):
    """Group-by via the VMEM hash table. Keys are packed into one int64
    word with the SAME range-compression layout as
    `packed_sort_group_aggregate` (kmins/key_bits from
    `ops.aggregate.key_pack_plan`), values accumulate as exact int64
    limbs.  Returns (out_batch, escaped, n_groups): `escaped > 0` means
    load-cap or probe-bound breach — the caller MUST discard the batch
    and radix-partition (exec/executor.Executor.hash_aggregate owns
    that loop).  Output capacity is `table_slots`; live = occupied."""
    n = batch.capacity
    packed = jnp.zeros(n, dtype=jnp.int64)
    for j, (ki, b) in enumerate(zip(key_indices, key_bits)):
        col = batch.columns[ki]
        norm = col.data.astype(jnp.int64) - kmins[j] + 1
        packed = (packed << b) | jnp.where(col.valid, norm, 0)
    slot = jnp.where(batch.live, hash_slot(packed, table_slots), -1)
    klo, khi = _split64(packed)

    layout, ns, nv = agg_layout(aggs)
    vbits = jnp.zeros(n, dtype=jnp.int32)
    vplanes: List[jax.Array] = [jnp.zeros(n, jnp.int32)] * nv
    for a, spec in enumerate(aggs):
        if spec.arg_index is None:
            bit = batch.live
        else:
            bit = batch.live & batch.columns[spec.arg_index].valid
        vbits = vbits | (bit.astype(jnp.int32) << a)
        kind, lo_p, hi_p, cnt_p, vlo_p, vhi_p = layout[a]
        if kind != _K_COUNT:
            col = batch.columns[spec.arg_index]
            v = jnp.where(bit, col.data.astype(jnp.int64), 0)
            vplanes[vlo_p], vplanes[vhi_p] = _split64(v)

    tk_lo, tk_hi, st, esc, occ = _hash_insert(
        slot, klo, khi, vbits, jnp.stack(vplanes), layout, table_slots,
        mode == "interpret")

    occupied = ~((tk_hi == _EMPTY_HI) & (tk_lo == _EMPTY_LO))
    key64 = _join64(tk_lo, tk_hi)

    out_cols: List[Column] = []
    rem = key64
    rev = []
    for j in range(len(key_indices) - 1, -1, -1):
        b = key_bits[j]
        digit = rem & ((1 << b) - 1)
        rem = rem >> b
        col = batch.columns[key_indices[j]]
        rev.append(Column(
            data=(digit - 1 + kmins[j]).astype(col.data.dtype),
            valid=occupied & (digit != 0)))
    out_cols.extend(reversed(rev))

    for a, spec in enumerate(aggs):
        kind, lo_p, hi_p, cnt_p, vlo_p, vhi_p = layout[a]
        cnt = st[cnt_p].astype(jnp.int64)
        if kind == _K_COUNT:
            out_cols.append(Column(data=cnt, valid=occupied))
            continue
        v64 = _join64(st[lo_p], st[hi_p])
        valid = occupied & (cnt > 0)
        if kind == _K_SUM:
            out_cols.append(Column(data=v64, valid=valid))
        else:
            dt = batch.columns[spec.arg_index].data.dtype
            out_cols.append(Column(data=v64.astype(dt), valid=valid))
    out = Batch(columns=tuple(out_cols), live=occupied)
    return out, esc.astype(jnp.int64), occ.astype(jnp.int64)


# --------------------------------------------------------------------------
# hybrid hash join: build = hash aggregation of row ids, probe = chained
# multi-plane gathers
# --------------------------------------------------------------------------

_JOIN_LAYOUT = ((_K_MIN, 0, 1, 2, 0, 1),)    # min(row_id) + its count


def join_table_slots(build_rows: int) -> Tuple[int, bool]:
    """(table_slots, fits) for a join build of `build_rows` candidate
    keys — same sizing rule as the aggregate table (3 state planes)."""
    cap = MIN_TABLE_SLOTS
    limit = min(MAX_TABLE_SLOTS, VMEM_TABLE_BYTES // (4 * 5))
    while cap * LOAD_NUM // LOAD_DEN < build_rows and cap < limit:
        cap *= 2
    return cap, cap * LOAD_NUM // LOAD_DEN >= build_rows


@recorded_jit(static_argnums=(1, 2, 3))
def build_join_table(build: Batch, build_keys: tuple, table_slots: int,
                     mode: str):
    """Hash-join build: insert every valid build key with min(row_id)
    as the payload (duplicate keys keep the smallest row, their count
    reveals them).  Returns (tk_lo, tk_hi, src [T] int32 row ids,
    dup_rows, escaped) — dup_rows > 0 breaks a unique-build contract,
    escaped > 0 means the table overflowed and the caller must degrade
    to the partitioned (hybrid) path."""
    from .join import _combined_key
    bk, bk_valid = _combined_key(build, build_keys)
    ok = build.live & bk_valid & (bk != EMPTY_KEY)
    forced = jnp.sum(build.live & bk_valid & (bk == EMPTY_KEY),
                     dtype=jnp.int64)
    slot = jnp.where(ok, hash_slot(bk, table_slots), -1)
    klo, khi = _split64(bk)
    rows = jnp.arange(build.capacity, dtype=jnp.int64)
    rlo, rhi = _split64(rows)
    vbits = ok.astype(jnp.int32)            # bit 0: min(row_id) valid
    tk_lo, tk_hi, st, esc, occ = _hash_insert(
        slot, klo, khi, vbits, jnp.stack([rlo, rhi]), _JOIN_LAYOUT,
        table_slots, mode == "interpret")
    n_ok = jnp.sum(ok, dtype=jnp.int64)
    escaped = esc.astype(jnp.int64) + forced
    dup_rows = n_ok - forced - esc.astype(jnp.int64) - \
        occ.astype(jnp.int64)
    return tk_lo, tk_hi, st[0], dup_rows, escaped


@recorded_jit(static_argnums=(5, 6, 7, 8))
def hash_join_probe(probe: Batch, build: Batch, tk_lo, tk_hi, src,
                    probe_keys: tuple, build_keys: tuple, kind: str,
                    gather_mode: str = "off"):
    """Probe a built (and dup/escape-validated) hash table: MAX_PROBES
    rounds of fused (key_lo, key_hi, row_id) gathers walk each probe's
    linear chain; an empty slot or an exhausted chain is a definitive
    miss (insertion never displaces past MAX_PROBES).  Payload columns
    materialize through the shared dense-join gather machinery
    (`ops.join._gather_build_payload`), riding the Pallas tiled gather
    when enabled.  Returns the joined batch; bit-exact vs the sorted
    searchsorted join."""
    from .join import _combined_key, _gather_build_payload
    table_slots = tk_lo.shape[0]
    pk, pk_valid = _combined_key(probe, probe_keys)
    ok = probe.live & pk_valid & (pk != EMPTY_KEY)
    slot = jnp.where(ok, hash_slot(pk, table_slots), 0)
    unresolved = ok
    found = jnp.full(probe.capacity, -1, dtype=jnp.int32)
    for _ in range(MAX_PROBES):
        from . import pallas_gather
        outs = pallas_gather.gather_columns(
            [tk_lo, tk_hi, src], slot,
            fills=[_EMPTY_LO, _EMPTY_HI, -1], mode=gather_mode)
        key_at = _join64(outs[0], outs[1])
        empty = key_at == EMPTY_KEY
        hit = unresolved & ~empty & (key_at == pk)
        found = jnp.where(hit, outs[2], found)
        unresolved = unresolved & ~empty & ~hit
        slot = jnp.where(slot + 1 >= table_slots, 0, slot + 1)
    matched = found >= 0
    if kind == "semi":
        return probe.with_live(probe.live & matched)
    if kind == "anti":
        return probe.with_live(probe.live & ~matched)
    src_c = jnp.clip(found, 0, build.capacity - 1)
    return _gather_build_payload(probe, build, src_c, matched, pk,
                                 build_keys, kind, gather_mode)


# --------------------------------------------------------------------------
# fused multiway star probe: k resident dimension tables, one pass
# --------------------------------------------------------------------------

MAX_MULTI_DIMS = 5           # q5-class stars top out here; the planner cap


def multiway_table_bytes(k: int, table_slots: int) -> int:
    """Resident VMEM footprint of k fused dimension tables: 3 int32
    planes each (key_lo, key_hi, src row id)."""
    return 3 * 4 * k * table_slots


def _multiprobe_kernel(k: int, table_slots: int):
    """Per fact block, walk all k probe chains in ONE kernel pass.

    Dimension planes arrive stacked [k, t_rows, LANES] and stay VMEM
    resident across the whole grid (index map pins them to block 0).
    Each row short-circuits: once it misses a dimension it is dead for
    every later one — exactly the ladder's live-mask AND, but without k
    intermediate materializations.  Per-dimension miss counters (rows
    that were still alive entering dimension d and failed there) ride
    an SMEM (1, k) accumulator, the `_insert_kernel` esc/occ pattern.
    """
    t_rows = table_slots // LANES

    def kernel(slot_ref, klo_ref, khi_ref, tk_lo, tk_hi, src_ref,
               found_ref, sc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            for d in range(k):
                sc_ref[0, d] = jnp.int32(0)

        def row(j, miss):
            r = j // LANES
            l = j % LANES
            # slot encoding: -2 dead fact row (skip entirely), -1 live
            # row whose key is NULL/sentinel (counts as a miss), else
            # the home slot.  The dead/live split is per row, so dim 0's
            # plane answers it for all dims.
            alive = slot_ref[0, r, l] != -2
            out_miss = []
            for d in range(k):
                slot = slot_ref[d, r, l]
                klo = klo_ref[d, r, l]
                khi = khi_ref[d, r, l]
                ok = alive & (slot >= 0)

                def probe_cond(c):
                    return c[2] == 0

                def probe_body(c, d=d):
                    s, p, _ = c
                    sr = s // LANES
                    sl = s % LANES
                    thi = tk_hi[d, sr, sl]
                    tlo = tk_lo[d, sr, sl]
                    empty = (thi == _EMPTY_HI) & (tlo == _EMPTY_LO)
                    match = (~empty) & (thi == khi) & (tlo == klo)
                    out = jnp.where(match, 1,
                                    jnp.where(empty, 3,
                                              0)).astype(jnp.int32)
                    p2 = p + jnp.int32(1)
                    out = jnp.where((out == 0) & (p2 >= MAX_PROBES),
                                    jnp.int32(3), out)
                    nxt = jnp.where(s + 1 >= table_slots, 0,
                                    s + 1).astype(jnp.int32)
                    return (jnp.where(out == 0, nxt, s), p2, out)

                s_f, _, outcome = jax.lax.while_loop(
                    probe_cond, probe_body,
                    (jnp.where(ok, slot, 0), jnp.int32(0),
                     jnp.where(ok, jnp.int32(0), jnp.int32(3))))
                hit = ok & (outcome == 1)
                sr = s_f // LANES
                sl = s_f % LANES
                found_ref[d, r, l] = jnp.where(
                    hit, src_ref[d, sr, sl], jnp.int32(-1))
                out_miss.append(
                    miss[d] + jnp.where(alive & ~hit,
                                        1, 0).astype(jnp.int32))
                alive = hit
            return tuple(out_miss)

        miss0 = tuple(sc_ref[0, d] for d in range(k))
        miss = jax.lax.fori_loop(0, BLOCK, row, miss0)
        for d in range(k):
            sc_ref[0, d] = miss[d]
    return kernel


@recorded_jit(static_argnums=(4, 5))
def multiway_probe(probe: Batch, tk_lo, tk_hi, src,
                   probe_keys: tuple, mode: str):
    """Fused star probe: k dup/escape-validated dimension tables
    (stacked `build_join_table` planes, ALL sized to one shared
    `table_slots` so the stack is rectangular) probed in a single
    Pallas pass over the fact batch.  `probe_keys` is a tuple of
    per-dimension fact-side key index tuples.  Returns
    (found [k, n] int32 build row ids, -1 = miss at-or-before that
    dimension; miss [k] int64 per-dimension miss counters) — payload
    gathers stay in the caller, which shares the dense-join machinery
    with the pairwise ladder for bit-exactness."""
    from .join import _combined_key
    k = len(probe_keys)
    table_slots = tk_lo.shape[1]
    slots, klos, khis = [], [], []
    for pk_idx in probe_keys:
        pk, pk_valid = _combined_key(probe, pk_idx)
        ok = probe.live & pk_valid & (pk != EMPTY_KEY)
        slot = jnp.where(ok, hash_slot(pk, table_slots),
                         jnp.where(probe.live, -1, -2))
        klo, khi = _split64(pk)
        slots.append(slot)
        klos.append(jnp.where(ok, klo, 0))
        khis.append(jnp.where(ok, khi, 0))
    n = probe.capacity
    slot = _pad_rows(jnp.stack(slots), -2)
    klo = _pad_rows(jnp.stack(klos), 0)
    khi = _pad_rows(jnp.stack(khis), 0)
    npad = slot.shape[-1]
    nb = npad // BLOCK
    t_rows = table_slots // LANES
    found, sc = pl.pallas_call(
        _multiprobe_kernel(k, table_slots),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((k, SUB, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, SUB, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, SUB, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, t_rows, LANES), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, t_rows, LANES), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, t_rows, LANES), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((k, SUB, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0),
                         memory_space=pltpu.SMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((k, nb * SUB, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, k), jnp.int32)],
        interpret=(mode == "interpret"),
    )(slot.reshape(k, nb * SUB, LANES),
      klo.reshape(k, nb * SUB, LANES),
      khi.reshape(k, nb * SUB, LANES),
      tk_lo.reshape(k, t_rows, LANES),
      tk_hi.reshape(k, t_rows, LANES),
      src.reshape(k, t_rows, LANES))
    return found.reshape(k, npad)[:, :n], sc[0].astype(jnp.int64)


def shard_join(probe: Batch, build: Batch, probe_keys: tuple,
               build_keys: tuple, kind: str, table_slots: int,
               mode: str, gather_mode: str = "off"):
    """Shard-local fused build + probe: the per-chip body of the
    mesh-partitioned join (parallel/stages.partitioned_hash_join_step).
    Deliberately NOT a jit entry of its own — it traces inside the
    enclosing shard_map program, so build, probe, and their validation
    counters stay in ONE XLA module with zero host round trips; the
    caller psums (dup_rows, escaped) across the mesh and owns the
    degrade decision (dup -> expansion join, escape -> skew, host
    equi-join). Returns (joined, dup_rows, escaped)."""
    tk_lo, tk_hi, src, dup_rows, escaped = build_join_table(
        build, build_keys, table_slots, mode)
    joined = hash_join_probe(probe, build, tk_lo, tk_hi, src,
                             probe_keys, build_keys, kind, gather_mode)
    return joined, dup_rows, escaped
