"""Sort / TopN / Limit kernels.

Reference: OrderByOperator over a PagesIndex with compiled comparators
(operator/OrderByOperator.java, sql/gen/OrderingCompiler.java:71) and
TopNOperator (operator/topn/). Here: one multi-operand `lax.sort` whose key
encoding bakes in direction and null placement, then a full-batch gather —
XLA's sort is a parallel bitonic-style network that suits the TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..exec.profiler import recorded_jit

from ..batch import Batch, Column


def _sort_key_encoding(col: Column, ascending: bool, nulls_first: bool):
    """Encode (valid, data) into operands whose ascending lexicographic
    order realizes the requested direction + null placement."""
    if nulls_first:
        null_rank = jnp.where(col.valid, 1, 0)
    else:
        null_rank = jnp.where(col.valid, 0, 1)
    # normalize NULL slots: garbage data must not order NULL rows among
    # themselves (window peer groups require NULLs to compare equal)
    data = jnp.where(col.valid, col.data, jnp.zeros((), col.data.dtype))
    if not ascending:
        if jnp.issubdtype(data.dtype, jnp.bool_):
            data = ~data
        elif jnp.issubdtype(data.dtype, jnp.floating):
            data = -data
        else:
            data = jnp.invert(data)   # order-reversing, overflow-safe
    return null_rank.astype(jnp.int8), data


@recorded_jit(static_argnums=(1, 2))
def sort_batch(batch: Batch, keys: tuple, limit) -> Batch:
    """keys: tuple of (col_index, ascending, nulls_first). Dead rows sort
    last; an optional limit marks only the first `limit` rows live (TopN)."""
    n = batch.capacity
    operands = [(~batch.live).astype(jnp.int8)]
    for (idx, asc, nf) in keys:
        nr, data = _sort_key_encoding(batch.columns[idx], asc, nf)
        operands.append(nr)
        operands.append(data)
    num_keys = len(operands)
    operands.append(jnp.arange(n, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]

    cols = tuple(Column(data=c.data[perm], valid=c.valid[perm])
                 for c in batch.columns)
    live = batch.live[perm]
    if limit is not None:
        live = live & (jnp.arange(n) < limit)
    return Batch(columns=cols, live=live)


def sort_pack_plan(batch: Batch, keys: tuple, fetch=None):
    """Range-compress integer ORDER BY keys into one int64 (direction and
    null placement baked into the rank encoding) so the big sort is
    always (packed, index) — measurement and bit layout shared with the
    aggregation kernels (ops.aggregate.key_pack_plan; the +3 slack there
    keeps the DESC rank range clear of the nulls-first slot 0 and the
    ASC range clear of the nulls-last slot 2^b - 1)."""
    from .aggregate import key_pack_plan
    return key_pack_plan(batch, tuple(idx for idx, _, _ in keys),
                         fetch=fetch)


@recorded_jit(static_argnums=(2, 3, 4))
def sort_batch_packed(batch: Batch, kmins, keys: tuple, key_bits: tuple,
                      limit) -> Batch:
    """sort_batch via one packed int64 key (see sort_pack_plan): rank
    within each key's field realizes ASC/DESC + null placement; dead
    rows pack to int64.max. The sort itself is 2 operands at any key
    count."""
    n = batch.capacity
    packed = jnp.zeros(n, dtype=jnp.int64)
    for j, ((idx, asc, nf), b) in enumerate(zip(keys, key_bits)):
        col = batch.columns[idx]
        span_max = (1 << b) - 1
        norm = col.data.astype(jnp.int64) - kmins[j] + 1
        rank = norm if asc else (span_max - 1) - norm
        null_slot = 0 if nf else span_max
        rank = jnp.where(col.valid, rank, null_slot)
        packed = (packed << b) | rank
    packed = jnp.where(batch.live, packed, jnp.iinfo(jnp.int64).max)
    idx_arr = jnp.arange(n, dtype=jnp.int32)
    _, perm = jax.lax.sort((packed, idx_arr), num_keys=1, is_stable=True)
    out_n = n
    if limit is not None and int(limit) < n:
        # TopN: dead rows sort last, so the winners live in the prefix —
        # slice the permutation BEFORE the payload gathers. The gathers
        # are the kernel's whole cost at scale (the sort itself is 2
        # operands); a LIMIT 10 over millions must not gather millions.
        from ..batch import bucket_capacity
        out_n = min(n, max(1024, bucket_capacity(int(limit))))
        perm = perm[:out_n]
    cols = tuple(Column(data=c.data[perm], valid=c.valid[perm])
                 for c in batch.columns)
    live = batch.live[perm]
    if limit is not None:
        live = live & (jnp.arange(out_n) < limit)
    return Batch(columns=cols, live=live)


@recorded_jit()
def limit_batch(batch: Batch, count: jax.Array) -> Batch:
    """Keep the first `count` live rows (in current order)."""
    rank = jnp.cumsum(batch.live.astype(jnp.int64)) - 1
    return batch.with_live(batch.live & (rank < count))
