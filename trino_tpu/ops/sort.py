"""Sort / TopN / Limit kernels.

Reference: OrderByOperator over a PagesIndex with compiled comparators
(operator/OrderByOperator.java, sql/gen/OrderingCompiler.java:71) and
TopNOperator (operator/topn/). Here: one multi-operand `lax.sort` whose key
encoding bakes in direction and null placement, then a full-batch gather —
XLA's sort is a parallel bitonic-style network that suits the TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..batch import Batch, Column


def _sort_key_encoding(col: Column, ascending: bool, nulls_first: bool):
    """Encode (valid, data) into operands whose ascending lexicographic
    order realizes the requested direction + null placement."""
    if nulls_first:
        null_rank = jnp.where(col.valid, 1, 0)
    else:
        null_rank = jnp.where(col.valid, 0, 1)
    # normalize NULL slots: garbage data must not order NULL rows among
    # themselves (window peer groups require NULLs to compare equal)
    data = jnp.where(col.valid, col.data, jnp.zeros((), col.data.dtype))
    if not ascending:
        if jnp.issubdtype(data.dtype, jnp.bool_):
            data = ~data
        elif jnp.issubdtype(data.dtype, jnp.floating):
            data = -data
        else:
            data = jnp.invert(data)   # order-reversing, overflow-safe
    return null_rank.astype(jnp.int8), data


@functools.partial(jax.jit, static_argnums=(1, 2))
def sort_batch(batch: Batch, keys: tuple, limit) -> Batch:
    """keys: tuple of (col_index, ascending, nulls_first). Dead rows sort
    last; an optional limit marks only the first `limit` rows live (TopN)."""
    n = batch.capacity
    operands = [(~batch.live).astype(jnp.int8)]
    for (idx, asc, nf) in keys:
        nr, data = _sort_key_encoding(batch.columns[idx], asc, nf)
        operands.append(nr)
        operands.append(data)
    num_keys = len(operands)
    operands.append(jnp.arange(n, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]

    cols = tuple(Column(data=c.data[perm], valid=c.valid[perm])
                 for c in batch.columns)
    live = batch.live[perm]
    if limit is not None:
        live = live & (jnp.arange(n) < limit)
    return Batch(columns=cols, live=live)


@jax.jit
def limit_batch(batch: Batch, count: jax.Array) -> Batch:
    """Keep the first `count` live rows (in current order)."""
    rank = jnp.cumsum(batch.live.astype(jnp.int64)) - 1
    return batch.with_live(batch.live & (rank < count))
