"""Group-by aggregation kernels — scatter-free, TPU-first.

Reference: Trino's HashAggregationOperator (operator/HashAggregationOperator.java:45)
with GroupByHash picking a strategy by key shape (GroupByHash.java:82-93 —
BigintGroupByHash vs FlatGroupByHash SWAR table), and compiled accumulators
(operator/aggregation/AccumulatorCompiler.java:88).

TPU constraints drive the redesign (measured on v5e: a 6-slot scatter-add
over 6M rows costs ~500ms because XLA TPU serializes scatters, while a full
masked reduction over the same rows costs ~0.1ms):

- **direct** (small dense domains — dictionary/boolean keys): group id is a
  mixed-radix code; each (group, aggregate) cell is a *masked full
  reduction*. XLA fuses the G x A reductions over one data pass; no scatter,
  no hash table. (The analog of BigintGroupByHash's dense mode.)
- **sort** (general keys): lexicographic multi-column `lax.sort` (dead rows
  last), segment boundaries by adjacent-difference, then per-aggregate:
  sums/counts via `cumsum` + boundary differencing, min/max via a segmented
  associative scan; group results land via `searchsorted` *gathers*, never
  scatters. Exact (sorts real key values, no hash collisions), static
  shapes throughout.
- **hash** (high cardinality — few rows per group): the VMEM-resident
  open-addressing table kernel in `ops/pallas_hash.py`, picked by the
  planner's rows-per-group gate and dispatched by
  `Executor.hash_aggregate`; keys pack losslessly through this module's
  `key_pack_plan` (range compression — equality stays exact, hash
  collisions can never merge groups) and every run keeps the sort kernel
  as its fallback (kernel off, unpackable keys, DISTINCT, escapes).

Both paths produce *partial aggregate states* (sum/count/min/max); AVG is
decomposed by the planner into (sum, count) and finalized in the
post-projection, like Trino's PARTIAL -> FINAL split. States merge across
shards with psum/all_gather collectives (parallel/exchange.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..exec.profiler import recorded_jit
from jax import lax

from ..batch import Batch, Column

AGG_FUNCS = ("sum", "count", "count_star", "min", "max")

# direct strategy is a G x A unrolled reduction graph; keep G bounded so
# compile time and graph size stay sane (planner enforces the same bound)
MAX_DIRECT_GROUPS = 64


@dataclass(frozen=True)
class AggSpec:
    func: str                 # one of AGG_FUNCS
    arg_index: Optional[int]  # column in the input batch (None for count_star)
    distinct: bool = False    # sum/count DISTINCT (sort strategy only)

    def __post_init__(self):
        assert self.func in AGG_FUNCS, self.func
        assert (self.arg_index is None) == (self.func == "count_star")
        assert not (self.distinct and self.func not in ("sum", "count"))


def _identity(func: str, dtype) -> object:
    if func == "sum" or func.startswith("count"):
        return 0
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if func == "min" else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if func == "min" else info.min


# --------------------------------------------------------------------------
# direct (dense small-domain) strategy — masked reductions
# --------------------------------------------------------------------------

@recorded_jit(static_argnums=(1, 2, 3))
def direct_group_aggregate(batch: Batch, key_indices: tuple,
                           domains: tuple, aggs: tuple) -> Batch:
    """Group by small-domain integer/dictionary keys.

    domains[i] = exclusive upper bound of key column i's values (dictionary
    size). Output has exactly prod(domains) rows; group g's keys decode as
    mixed-radix digits of g. Groups with no rows are not live.
    """
    out_capacity = 1
    for d in domains:
        out_capacity *= d
    assert out_capacity <= MAX_DIRECT_GROUPS, \
        "direct strategy domain too large; planner should pick sort"

    gid = jnp.zeros(batch.capacity, dtype=jnp.int32)
    key_valid = jnp.ones(batch.capacity, dtype=jnp.bool_)
    for ki, d in zip(key_indices, domains):
        col = batch.columns[ki]
        gid = gid * d + jnp.clip(col.data.astype(jnp.int32), 0, d - 1)
        key_valid = key_valid & col.valid
    contributes = batch.live & key_valid

    # per-group boolean masks, reused across aggregates (XLA keeps these
    # fused into the reduction pass; nothing is materialized at [n, G])
    group_masks = [contributes & (gid == g) for g in range(out_capacity)]
    group_count = jnp.stack([m.sum(dtype=jnp.int64) for m in group_masks])
    group_live = group_count > 0

    out_cols = []
    g_idx = jnp.arange(out_capacity, dtype=jnp.int32)
    radix = out_capacity
    for ki, d in zip(key_indices, domains):
        radix //= d
        digit = (g_idx // radix) % d
        out_cols.append(Column(
            data=digit.astype(batch.columns[ki].data.dtype),
            valid=group_live))

    for spec in aggs:
        if spec.func == "count_star":
            out_cols.append(Column(data=group_count, valid=group_live))
            continue
        col = batch.columns[spec.arg_index]
        data = col.data
        if spec.func == "count":
            cnt = jnp.stack([(m & col.valid).sum(dtype=jnp.int64)
                             for m in group_masks])
            out_cols.append(Column(data=cnt, valid=group_live))
            continue
        cnt = jnp.stack([(m & col.valid).sum(dtype=jnp.int64)
                         for m in group_masks])
        if spec.func == "sum":
            acc_dtype = jnp.int64 if jnp.issubdtype(data.dtype, jnp.integer) \
                else data.dtype
            vals = data.astype(acc_dtype)
            state = jnp.stack([
                jnp.where(m & col.valid, vals, 0).sum() for m in group_masks])
        else:
            ident = _identity(spec.func, data.dtype)
            red = jnp.min if spec.func == "min" else jnp.max
            state = jnp.stack([
                red(jnp.where(m & col.valid, data, ident))
                for m in group_masks])
        out_cols.append(Column(data=state, valid=group_live & (cnt > 0)))
    return Batch(columns=tuple(out_cols), live=group_live)


# --------------------------------------------------------------------------
# sort-based general strategy — cumsum / segmented scan, gather-only
# --------------------------------------------------------------------------

def _segmented_scan(vals: jax.Array, boundary: jax.Array, op):
    """Inclusive segmented scan: position i holds op-reduction of its
    segment's values up to i. boundary[i]=True starts a new segment."""
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))
    _, out = lax.associative_scan(combine, (boundary, vals))
    return out


@recorded_jit(static_argnums=(1, 2, 3, 4))
def sort_group_aggregate(batch: Batch, key_indices: tuple, aggs: tuple,
                         out_capacity: int,
                         gather_mode: str = "off") -> Batch:
    """Group by arbitrary key columns via lexicographic sort.

    Exact (sorts real key values, not hashes). Output capacity is a static
    bound; if the true group count exceeds it, excess groups are dropped —
    callers size it from stats and the executor grows + retries on
    overflow (SURVEY.md §7 hard part 1). NULL keys group together (SQL
    GROUP BY treats NULLs as equal).

    Scatter-free: group states are read out of running scans at segment-end
    positions located with searchsorted.
    """
    n = batch.capacity
    operands = [(~batch.live).astype(jnp.int8)]
    for ki in key_indices:
        col = batch.columns[ki]
        operands.append((~col.valid).astype(jnp.int8))
        # NULL keys must form ONE group: normalize masked-out data so the
        # boundary detector can't split NULL rows on garbage values
        operands.append(jnp.where(col.valid, col.data,
                                  jnp.zeros((), col.data.dtype)))
    n_group_ops = len(operands)
    # DISTINCT aggregate columns join the sort key (after the group keys) so
    # duplicates within a group are adjacent; they do NOT define segment
    # boundaries. At most one distinct column (planner enforces).
    distinct_cols = sorted({s.arg_index for s in aggs if s.distinct})
    distinct_pos = {}
    for di in distinct_cols:
        col = batch.columns[di]
        distinct_pos[di] = len(operands)
        operands.append((~col.valid).astype(jnp.int8))
        operands.append(col.data)
    num_keys = len(operands)
    operands.append(jnp.arange(n, dtype=jnp.int32))   # payload: row index
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    live_s = batch.live[perm]

    diff = jnp.zeros(n, dtype=jnp.bool_)
    for op in sorted_ops[1:n_group_ops]:  # key operands only (skip dead flag)
        diff = diff | (op != jnp.roll(op, 1))
    first = jnp.arange(n) == 0
    boundary = live_s & (first | diff)

    # distinct markers: first occurrence of each distinct valid value
    # within a group (the distinct column participates in the sort, so
    # duplicates are adjacent — Trino: MarkDistinct + filtered accumulator)
    distinct_fresh = {}
    for di in distinct_cols:
        p = distinct_pos[di]
        dvinv_s, ddata_s = sorted_ops[p], sorted_ops[p + 1]
        distinct_fresh[di] = boundary | \
            (ddata_s != jnp.roll(ddata_s, 1)) | \
            (dvinv_s != jnp.roll(dvinv_s, 1))
    return _grouped_reduce(batch, key_indices, aggs, out_capacity, perm,
                           live_s, boundary, distinct_fresh, gather_mode)


def _grouped_reduce(batch: Batch, key_indices: tuple, aggs: tuple,
                    out_capacity: int, perm, live_s, boundary,
                    distinct_fresh, gather_mode: str = "off") -> Batch:
    """Shared segment machinery for the sorted aggregation kernels: given
    the sort permutation and group boundaries, locate segment extents and
    reduce every aggregate — used by both the general multi-operand kernel
    and the packed 2-operand kernel (traced inside their jits).

    `gather_mode` routes the GROUP READBACK gathers (representative row
    per output group -> key columns) through the Pallas tiled-gather
    kernel (ops/pallas_gather.py): one index decomposition feeds every
    key data/validity plane. The kernel's win region is small batches
    (its scan cost grows with the gathered table's length), so the
    shape gate falls back to the jnp.take path at scale — bit-exact
    either way."""
    n = batch.capacity
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1      # 0-based group id
    num_groups = boundary.sum()

    g = jnp.arange(out_capacity)
    group_live = g < num_groups
    # segment extents per output group: scatter each boundary position at
    # its group id (unique indices), then end[g] = start[g+1] - 1 — one
    # scatter + one gather instead of two searchsorteds (searchsorted
    # lowers to ~24 serial gather rounds; pathological at 10M+ rows)
    pos = jnp.arange(n, dtype=jnp.int32)
    sidx = jnp.where(boundary & (seg < out_capacity), seg, out_capacity)
    start_lut = jnp.zeros(out_capacity + 1, dtype=jnp.int32)
    start_lut = start_lut.at[sidx].max(pos, mode="drop")
    start_c = jnp.clip(start_lut[:out_capacity], 0, n - 1)
    next_start = start_lut[jnp.clip(g + 1, 0, out_capacity)]
    end_pos = jnp.where(g + 1 < num_groups,
                        jnp.clip(next_start - 1, 0, n - 1), n - 1)

    out_cols = []
    key_tables = []
    for ki in key_indices:
        key_tables.extend((batch.columns[ki].data,
                           batch.columns[ki].valid))
    from . import pallas_gather
    if gather_mode != "off" and \
            pallas_gather.gather_supported([perm] + key_tables):
        # the group gather: ONE fused pass resolves the representative
        # row (perm at segment starts) and every key data/valid plane
        rep = pallas_gather.gather_columns([perm], start_c,
                                           mode=gather_mode)[0]
        outs = pallas_gather.gather_columns(key_tables, rep,
                                            mode=gather_mode)
        for j, ki in enumerate(key_indices):
            out_cols.append(Column(data=outs[2 * j],
                                   valid=outs[2 * j + 1] & group_live))
    else:
        rep = perm[start_c]               # representative row per group
        for ki in key_indices:
            col = batch.columns[ki]
            out_cols.append(Column(data=col.data[rep],
                                   valid=col.valid[rep] & group_live))

    def seg_total(values_sorted):
        """Per-group totals of a sorted value array via cumsum diff."""
        cs = jnp.cumsum(values_sorted)
        upto_end = cs[end_pos]
        before_start = jnp.where(start_c > 0, cs[jnp.clip(start_c - 1,
                                                          0, n - 1)], 0)
        return jnp.where(group_live, upto_end - before_start, 0)

    for spec in aggs:
        if spec.func == "count_star":
            cnt = seg_total(live_s.astype(jnp.int64))
            out_cols.append(Column(data=cnt, valid=group_live))
            continue
        col = batch.columns[spec.arg_index]
        data_s = col.data[perm]
        valid_s = col.valid[perm] & live_s
        if spec.distinct:
            marker = valid_s & distinct_fresh[spec.arg_index]
            if spec.func == "count":
                out_cols.append(Column(data=seg_total(
                    marker.astype(jnp.int64)), valid=group_live))
            else:  # sum distinct
                acc_dtype = jnp.int64 if jnp.issubdtype(
                    col.data.dtype, jnp.integer) else col.data.dtype
                vals = jnp.where(marker, data_s.astype(acc_dtype), 0)
                cnt = seg_total(marker.astype(jnp.int64))
                out_cols.append(Column(data=seg_total(vals),
                                       valid=group_live & (cnt > 0)))
            continue
        cnt = seg_total(valid_s.astype(jnp.int64))
        if spec.func == "count":
            out_cols.append(Column(data=cnt, valid=group_live))
            continue
        if spec.func == "sum":
            acc_dtype = jnp.int64 if jnp.issubdtype(col.data.dtype,
                                                    jnp.integer) \
                else col.data.dtype
            vals = jnp.where(valid_s, data_s.astype(acc_dtype), 0)
            state = seg_total(vals)
        else:
            ident = _identity(spec.func, col.data.dtype)
            vals = jnp.where(valid_s, data_s, ident)
            op = jnp.minimum if spec.func == "min" else jnp.maximum
            scanned = _segmented_scan(vals, boundary, op)
            state = jnp.where(group_live, scanned[end_pos], ident)
        out_cols.append(Column(data=state, valid=group_live & (cnt > 0)))
    return Batch(columns=tuple(out_cols), live=group_live)


# --------------------------------------------------------------------------
# packed sort strategy — range-compressed keys, 2-operand sort
# --------------------------------------------------------------------------

def key_pack_plan(batch: Batch, key_indices: tuple, fetch=None):
    """Measure per-key [min, max] on device (ONE fused fetch) and derive a
    static packing layout: key i occupies ceil(log2(span+3)) bits; slot 0
    and the top slot stay free for NULL placement and direction
    reversal. Returns (kmins host array, bits tuple) or None if the
    combined width exceeds 62 bits or a key isn't integer-typed.

    Why: XLA TPU compile cost for lax.sort is dominated by OPERAND COUNT
    (measured v5e: 2 operands ~40s, 4 ~170s, 6 ~460s, nearly flat in
    rows). Collapsing any number of integer keys into ONE int64 keeps
    every big sort at (packed, index) — the same range-compression idea
    as BigintGroupByHash's dense path, applied to the sort domain."""
    # `fetch` (the executor's cross-run decision cache) turns the
    # min/max measurement into a zero-round-trip host decision on
    # re-execution
    plan = _measure_key_bits(batch, key_indices, fetch)
    if plan is None:
        return None
    kmins, bits = plan
    if sum(bits) > 62:
        return None
    return kmins, bits


def key_pack_plan_words(batch: Batch, key_indices: tuple, fetch=None,
                        max_words: int = 3):
    """key_pack_plan generalized to MULTIPLE packed words: keys are
    assigned IN ORDER to words of <=62 bits each, and the sort becomes
    an LSD-radix sequence of stable 2-operand sorts (least-significant
    word first) — wide GROUP BYs (TPC-H q10's 7 keys ~ 111 bits) stay
    at compile-cheap operand counts instead of exploding into the
    general kernel's 2-per-key sort. Returns (kmins, bits, word_splits)
    where word_splits are (start, end) key ranges per word; None when
    any single key exceeds 62 bits, a key isn't integer-typed, or more
    than max_words words would be needed."""
    plan = _measure_key_bits(batch, key_indices, fetch)
    if plan is None:
        return None
    kmins, bits = plan
    splits = []
    start, cur = 0, 0
    for i, b in enumerate(bits):
        if b > 62:
            return None
        if cur + b > 62:
            splits.append((start, i))
            start, cur = i, 0
        cur += b
    splits.append((start, len(bits)))
    if len(splits) > max_words:
        return None
    return kmins, bits, tuple(splits)


def _measure_key_bits(batch: Batch, key_indices: tuple, fetch=None):
    """Shared measurement: per-key [min, max] -> (kmins, bits) with no
    total-width cap (key_pack_plan applies the single-word cap)."""
    import numpy as np
    stats = []
    for ki in key_indices:
        col = batch.columns[ki]
        if not jnp.issubdtype(col.data.dtype, jnp.integer) and \
                col.data.dtype != jnp.bool_:
            return None
        m = batch.live & col.valid
        data = col.data.astype(jnp.int64)
        big = jnp.iinfo(jnp.int64)
        stats.append(jnp.min(jnp.where(m, data, big.max)))
        stats.append(jnp.max(jnp.where(m, data, big.min)))
    vals = fetch(*stats) if fetch is not None else \
        np.asarray(jnp.stack(stats))
    kmins, bits = [], []
    for i in range(len(key_indices)):
        lo, hi = int(vals[2 * i]), int(vals[2 * i + 1])
        if hi < lo:
            lo, hi = 0, 0
        kmins.append(lo)
        bits.append(max(2, int(hi - lo + 3).bit_length()))
    return np.asarray(kmins, dtype=np.int64), tuple(bits)


@recorded_jit(static_argnums=(2, 3, 4, 5, 6, 7))
def packed_sort_group_aggregate(batch: Batch, kmins, key_indices: tuple,
                                key_bits: tuple, aggs: tuple,
                                out_capacity: int,
                                word_splits: tuple = None,
                                gather_mode: str = "off") -> Batch:
    """sort_group_aggregate with all keys packed into int64 words (see
    key_pack_plan / key_pack_plan_words). One word sorts directly;
    multiple words run an LSD radix: stable 2-operand sorts from the
    least-significant word up, so even 7-key GROUP BYs never exceed two
    sort operands per pass (XLA TPU sort compile cost is operand-count
    bound). Dead rows pack to int64.max in every word so they sort
    last; group keys are read back from representative rows (gathers at
    G positions, not N). No DISTINCT support (callers route distinct to
    the general kernel)."""
    n = batch.capacity
    if word_splits is None:
        word_splits = ((0, len(key_indices)),)
    words = []
    for (s, e) in word_splits:
        w = jnp.zeros(n, dtype=jnp.int64)
        for j in range(s, e):
            col = batch.columns[key_indices[j]]
            norm = col.data.astype(jnp.int64) - kmins[j] + 1
            norm = jnp.where(col.valid, norm, 0)      # NULL slot
            w = (w << key_bits[j]) | norm
        words.append(jnp.where(batch.live, w,
                               jnp.iinfo(jnp.int64).max))
    idx = jnp.arange(n, dtype=jnp.int32)
    perm = idx
    for w in reversed(words):             # LSD over words
        _, perm = jax.lax.sort((w[perm], perm), num_keys=1,
                               is_stable=True)
    live_s = batch.live[perm]

    first = jnp.arange(n) == 0
    diff = jnp.zeros(n, dtype=jnp.bool_)
    for w in words:
        ws = w[perm]
        diff = diff | (ws != jnp.roll(ws, 1))
    boundary = live_s & (first | diff)
    return _grouped_reduce(batch, key_indices, aggs, out_capacity, perm,
                           live_s, boundary, {}, gather_mode)


# --------------------------------------------------------------------------
# global (ungrouped) aggregation — Trino's AggregationOperator
# --------------------------------------------------------------------------

@recorded_jit(static_argnums=(1,))
def global_aggregate(batch: Batch, aggs: tuple) -> Batch:
    """No GROUP BY: one output row, always live (SQL: aggregates over an
    empty input produce one row of NULLs / zero counts). Pure masked
    reductions."""
    out_cols = []
    one = jnp.ones(1, dtype=jnp.bool_)
    for spec in aggs:
        if spec.func == "count_star":
            cnt = batch.live.sum(dtype=jnp.int64)[None]
            out_cols.append(Column(data=cnt, valid=one))
            continue
        col = batch.columns[spec.arg_index]
        m = batch.live & col.valid
        cnt = m.sum(dtype=jnp.int64)[None]
        if spec.func == "count":
            out_cols.append(Column(data=cnt, valid=one))
            continue
        if spec.func == "sum":
            acc_dtype = jnp.int64 if jnp.issubdtype(col.data.dtype,
                                                    jnp.integer) \
                else col.data.dtype
            state = jnp.where(m, col.data.astype(acc_dtype), 0).sum()[None]
        else:
            ident = _identity(spec.func, col.data.dtype)
            red = jnp.min if spec.func == "min" else jnp.max
            state = red(jnp.where(m, col.data, ident))[None]
        out_cols.append(Column(data=state, valid=cnt > 0))
    return Batch(columns=tuple(out_cols), live=one)


# --------------------------------------------------------------------------
# host-side finalizers (AVG quotient etc.)
# --------------------------------------------------------------------------

def avg_decimal_finalize(sums, counts, xp=np):
    """Exact decimal AVG: round-half-away-from-zero of sum/count at the
    input scale (Trino avg(decimal) keeps the argument scale).

    Works with either numpy (host finalization) or jax.numpy (device, used
    by the DecimalAvg IR node in ops/project.py) — single implementation so
    the subtle signed-remainder rounding cannot drift between paths."""
    counts = xp.where(counts == 0, 1, counts)
    q = sums // counts
    rem = sums - q * counts
    # adjust toward zero first (floor for negatives), then round
    neg = sums < 0
    q = xp.where(neg & (rem != 0), q + 1, q)
    rem = xp.where(neg, sums - q * counts, rem)
    up = (2 * xp.abs(rem) >= counts).astype(xp.int64)
    return xp.where(neg, q - up, q + up)
