"""Group-by aggregation kernels.

Reference: Trino's HashAggregationOperator (operator/HashAggregationOperator.java:45)
with GroupByHash picking a strategy by key shape (GroupByHash.java:82-93 —
BigintGroupByHash vs FlatGroupByHash SWAR table), and compiled accumulators
(operator/aggregation/AccumulatorCompiler.java:88).

TPUs have no efficient pointer-chasing hash table, so the strategies are
re-designed (SURVEY.md §7):

- **direct**: when every group key is dictionary/boolean/small-domain, the
  group id is a mixed-radix combination of codes and accumulators are a
  dense [domain]-sized table updated with scatter-add — one XLA scatter per
  aggregate, no hashing at all. (The analog of BigintGroupByHash's dense
  small-range mode.)
- **sort**: general keys: lexicographic multi-column `lax.sort` (dead rows
  sorted last), segment boundaries by adjacent-difference, segment ids by
  cumsum, then scatter-add into a bounded output table. Exact (no hash
  collisions), static shapes throughout.

Both paths produce *partial aggregate states* (sum/count/min/max); AVG is
decomposed by the planner into (sum, count) and finalized host-side, exactly
like Trino's PARTIAL -> FINAL split (HashAggregationOperator PARTIAL/FINAL
steps). Partial states from different shards merge with `psum`/second-pass
aggregation because every state is itself sum/min/max-mergeable.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import Batch, Column

# Aggregate functions and their merge ops. 'count' counts valid args;
# 'count_star' counts live rows.
AGG_FUNCS = ("sum", "count", "count_star", "min", "max")


@dataclass(frozen=True)
class AggSpec:
    func: str                 # one of AGG_FUNCS
    arg_index: Optional[int]  # column in the input batch (None for count_star)

    def __post_init__(self):
        assert self.func in AGG_FUNCS, self.func
        assert (self.arg_index is None) == (self.func == "count_star")


def _identity(func: str, dtype) -> object:
    if func == "sum" or func.startswith("count"):
        return 0
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if func == "min" else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if func == "min" else info.min


def _accumulate(spec: AggSpec, batch: Batch, gid: jax.Array,
                contributes: jax.Array, out_capacity: int):
    """Scatter one aggregate into a [out_capacity] table. Returns
    (state, state_valid_count) where state_valid_count counts contributing
    rows (used for NULL-ness of min/max/sum: empty group -> NULL)."""
    if spec.func == "count_star":
        mask = contributes
        vals = mask.astype(jnp.int64)
        init = jnp.zeros(out_capacity, dtype=jnp.int64)
        state = init.at[gid].add(vals, mode="drop")
        return state, state

    col = batch.columns[spec.arg_index]
    mask = contributes & col.valid
    safe_gid = jnp.where(mask, gid, out_capacity)  # dropped when masked
    cnt = jnp.zeros(out_capacity, dtype=jnp.int64
                    ).at[safe_gid].add(1, mode="drop")
    if spec.func == "count":
        return cnt, cnt
    data = col.data
    if spec.func == "sum":
        acc_dtype = jnp.int64 if jnp.issubdtype(data.dtype, jnp.integer) \
            else data.dtype
        init = jnp.zeros(out_capacity, dtype=acc_dtype)
        state = init.at[safe_gid].add(data.astype(acc_dtype), mode="drop")
        return state, cnt
    ident = _identity(spec.func, data.dtype)
    init = jnp.full(out_capacity, ident, dtype=data.dtype)
    if spec.func == "min":
        state = init.at[safe_gid].min(data, mode="drop")
    else:
        state = init.at[safe_gid].max(data, mode="drop")
    return state, cnt


# --------------------------------------------------------------------------
# direct (dense small-domain) strategy
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def direct_group_aggregate(batch: Batch, key_indices: tuple,
                           domains: tuple, aggs: tuple) -> Batch:
    """Group by small-domain integer/dictionary keys.

    domains[i] = exclusive upper bound of key column i's values (dictionary
    size). Output has exactly prod(domains) rows; group g's keys decode as
    mixed-radix digits of g. Groups with no rows are not live.
    """
    out_capacity = 1
    for d in domains:
        out_capacity *= d
    gid = jnp.zeros(batch.capacity, dtype=jnp.int32)
    key_valid = jnp.ones(batch.capacity, dtype=jnp.bool_)
    for ki, d in zip(key_indices, domains):
        col = batch.columns[ki]
        gid = gid * d + jnp.clip(col.data.astype(jnp.int32), 0, d - 1)
        key_valid = key_valid & col.valid
    contributes = batch.live & key_valid
    safe_gid = jnp.where(contributes, gid, out_capacity)

    group_count = jnp.zeros(out_capacity, dtype=jnp.int64
                            ).at[safe_gid].add(1, mode="drop")
    group_live = group_count > 0

    # decode keys from group index (mixed radix, most-significant first)
    out_cols = []
    g = jnp.arange(out_capacity, dtype=jnp.int32)
    radix = out_capacity
    for ki, d in zip(key_indices, domains):
        radix //= d
        digit = (g // radix) % d
        out_cols.append(Column(
            data=digit.astype(batch.columns[ki].data.dtype),
            valid=group_live))
    for spec in aggs:
        state, cnt = _accumulate(spec, batch, safe_gid, contributes,
                                 out_capacity)
        if spec.func.startswith("count"):
            valid = group_live
        else:
            valid = group_live & (cnt > 0)
        out_cols.append(Column(data=state, valid=valid))
    return Batch(columns=tuple(out_cols), live=group_live)


# --------------------------------------------------------------------------
# sort-based general strategy
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def sort_group_aggregate(batch: Batch, key_indices: tuple, aggs: tuple,
                         out_capacity: int) -> Batch:
    """Group by arbitrary key columns via lexicographic sort.

    Exact (sorts real key values, not hashes). Output capacity is a static
    bound; if the true group count exceeds it, excess groups are dropped —
    callers size it from stats (DeterminePartitionCount-style) or use
    revised bounds on overflow (executor re-plans, SURVEY.md §7 hard part 1).
    NULL keys group together (SQL GROUP BY treats NULLs as equal).
    """
    n = batch.capacity
    # sort keys: dead-rows-last flag, then (valid, data) per key column so
    # NULLs form their own group, then original index as payload
    operands = [(~batch.live).astype(jnp.int8)]
    for ki in key_indices:
        col = batch.columns[ki]
        operands.append((~col.valid).astype(jnp.int8))
        operands.append(col.data)
    num_keys = len(operands)
    operands.append(jnp.arange(n, dtype=jnp.int32))   # payload: row index
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    live_s = batch.live[perm]

    diff = jnp.zeros(n, dtype=jnp.bool_)
    for op in sorted_ops[:-1][1:]:  # skip dead-flag; keys only
        diff = diff | (op != jnp.roll(op, 1))
    first = jnp.arange(n) == 0
    boundary = live_s & (first | diff)
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1      # 0-based group id
    num_groups = boundary.sum()

    # map group id back to each *original* row for scatter accumulation
    gid_by_row = jnp.zeros(n, dtype=jnp.int32
                           ).at[perm].set(seg.astype(jnp.int32))
    contributes = batch.live
    safe_gid = jnp.where(contributes, gid_by_row, out_capacity)

    # representative source row for each group's key values
    rep = jnp.full(out_capacity, 0, dtype=jnp.int32)
    scatter_idx = jnp.where(boundary, seg, out_capacity)
    rep = rep.at[scatter_idx].set(perm, mode="drop")
    group_ids = jnp.arange(out_capacity)
    group_live = group_ids < num_groups

    out_cols = []
    for ki in key_indices:
        col = batch.columns[ki]
        out_cols.append(Column(data=col.data[rep],
                               valid=col.valid[rep] & group_live))
    for spec in aggs:
        state, cnt = _accumulate(spec, batch, safe_gid, contributes,
                                 out_capacity)
        if spec.func.startswith("count"):
            valid = group_live
        else:
            valid = group_live & (cnt > 0)
        out_cols.append(Column(data=state, valid=valid))
    return Batch(columns=tuple(out_cols), live=group_live)


# --------------------------------------------------------------------------
# global (ungrouped) aggregation — Trino's AggregationOperator
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1,))
def global_aggregate(batch: Batch, aggs: tuple) -> Batch:
    """No GROUP BY: one output row, always live (SQL: aggregates over an
    empty input produce one row of NULLs / zero counts)."""
    out_cols = []
    one = jnp.ones(1, dtype=jnp.bool_)
    gid = jnp.zeros(batch.capacity, dtype=jnp.int32)
    for spec in aggs:
        state, cnt = _accumulate(spec, batch, gid, batch.live, 1)
        if spec.func.startswith("count"):
            valid = one
        else:
            valid = cnt > 0
        out_cols.append(Column(data=state, valid=valid))
    return Batch(columns=tuple(out_cols), live=one)


# --------------------------------------------------------------------------
# host-side finalizers (AVG quotient etc.) — run on compacted outputs
# --------------------------------------------------------------------------

def avg_decimal_finalize(sums, counts, xp=np):
    """Exact decimal AVG: round-half-away-from-zero of sum/count at the
    input scale (Trino avg(decimal) keeps the argument scale).

    Works with either numpy (host finalization) or jax.numpy (device, used
    by the DecimalAvg IR node in ops/project.py) — single implementation so
    the subtle signed-remainder rounding cannot drift between paths."""
    counts = xp.where(counts == 0, 1, counts)
    q = sums // counts
    rem = sums - q * counts
    # adjust toward zero first (floor for negatives), then round
    neg = sums < 0
    q = xp.where(neg & (rem != 0), q + 1, q)
    rem = xp.where(neg, sums - q * counts, rem)
    up = (2 * xp.abs(rem) >= counts).astype(xp.int64)
    return xp.where(neg, q - up, q + up)
