"""Pallas TPU tiled-gather kernel — the dense-join probe as a native kernel.

XLA's gather on this backend issues ~8-15 ns per gathered element
regardless of table size (BENCH_NOTES round 5), and a probe site pays
that once PER PAYLOAD COLUMN.  This kernel restructures the probe around
what the hardware is actually good at — (8,128)-aligned VMEM tiles and
per-lane `take_along_axis` (the only gather form Mosaic lowers natively)
— and fuses the per-row index arithmetic (windowed-LUT offset, validity
mask, miss sentinel) with a MULTI-TABLE gather so each probe index is
decomposed once and every payload plane rides the same row/lane split.

Two kernel modes, one contract (`out[t][i] = tables[t][idx[i]]` for
`0 <= idx[i] < W`, `fill[t]` otherwise — bit-exact vs `jnp.take` on the
shared domain):

- **scan mode** (`gather_columns`): the table streams through VMEM in
  SLAB-row slabs on a second grid dimension; each probe tile tests its
  indices against every slab row and selects via a lane gather.  Per
  element the cost is ~W/(8*128) VPU ops, so it beats the XLA gather
  only for SMALL tables (dimension LUTs, validation words); above
  SCAN_MAX_ELEMS the wrapper falls back to `jnp.take` automatically.
- **windowed mode** (`gather_word_windowed`): for NEAR-SORTED probe keys
  (the chunked driver's fact scans — l_orderkey is ascending), each
  (8,128)-tile picks ONE WIN-sized window of the LUT via a
  scalar-prefetched block index (PrefetchScalarGridSpec: the per-tile
  minimum key, computed in XLA, selects the DMA'd block), then resolves
  all 1024 indices against that window in WIN_ROWS lane-gather rounds.
  Per element that is ~WIN/(8*128) VPU ops INDEPENDENT of table size —
  the sub-4 ns/element regime the round-5 break-even asks for.  Indices
  escaping their tile's window come back as misses and are COUNTED; the
  caller must treat a nonzero escape total exactly like the windowed-LUT
  escape flag it already owns (exec/chunked.py reruns the plain
  program), so correctness never rests on the near-sorted guess.

int64/float64 tables ride as two int32 bit-planes (Mosaic has no 64-bit
lanes; same trick as ops/pallas_agg.py); float32 bitcasts; narrow ints
and bools widen to one int32 plane.  Everything reassembles bit-exactly.

Reference role: Trino's compiled probe specialization — runtime bytecode
generation fusing the hash lookup with per-channel page building
(sql/gen/JoinProbeCompiler, PageJoiner.java:138) — re-expressed as a
hand-written TPU kernel, per the co-processing literature's finding that
probe-side gather/materialization is where accelerator joins win or
lose (PAPERS.md: Revisiting Co-Processing for Hash Joins; Global Hash
Tables Strike Back!).

Session wiring: `enable_pallas_gather` = auto (on for TPU backends) |
true (TPU: compiled; CPU: interpret mode — tier-1 runs the kernel logic
through the Pallas interpreter) | false.  Every call site keeps the
`jnp.take` path and falls back to it whenever the mode is off or the
shape is outside the kernel's win region.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SUB = 8                     # sublanes per probe tile
LANES = 128                 # lanes per probe tile
TILE = SUB * LANES          # probe indices resolved per grid step
SLAB_ROWS = 16              # scan mode: LUT rows (of LANES) per slab
SLAB = SLAB_ROWS * LANES
WIN_ROWS = 64               # windowed mode: rows per per-tile window
WIN = WIN_ROWS * LANES      # 8192 LUT entries per tile window
MAX_PLANES = 12             # int32 planes per pallas_call (VMEM budget)
# scan mode's per-element cost is ~W/(SUB*LANES) VPU ops; beyond this the
# XLA gather's flat ~8-15 ns/element wins (v5e break-even measurement)
SCAN_MAX_ELEMS = 1 << 16
# windowed indices are 32-bit in-kernel
MAX_WINDOWED_ELEMS = (1 << 31) - 1


def resolve_mode(setting) -> str:
    """Session-property value -> kernel mode: 'device' (compiled TPU
    kernel), 'interpret' (Pallas interpreter — the CPU/tier-1 path), or
    'off' (every site uses its jnp.take fallback)."""
    s = str(setting).lower()
    on_tpu = jax.default_backend() == "tpu"
    if s in ("true", "1"):
        return "device" if on_tpu else "interpret"
    if s == "auto":
        return "device" if on_tpu else "off"
    return "off"


# --------------------------------------------------------------------------
# int32 plane split / reassembly (bit-exact for every engine lane dtype)
# --------------------------------------------------------------------------

def plane_count(dtype) -> int:
    return 2 if jnp.dtype(dtype).itemsize == 8 else 1


def supports_tables(tables) -> bool:
    """Can every table ride int32 planes? (all engine lane dtypes can;
    the guard exists for exotic inputs like object-backed arrays)."""
    for t in tables:
        dt = jnp.dtype(t.dtype)
        if not (jnp.issubdtype(dt, jnp.integer) or
                jnp.issubdtype(dt, jnp.floating) or dt == jnp.bool_):
            return False
        if dt.itemsize > 8:
            return False
    return True


def _split_planes(t: jax.Array) -> List[jax.Array]:
    """Table -> little-endian int32 planes ([lo, hi] for 8-byte lanes)."""
    dt = jnp.dtype(t.dtype)
    if dt.itemsize == 8:
        pair = jax.lax.bitcast_convert_type(t, jnp.int32)   # [..., 2]
        return [pair[..., 0], pair[..., 1]]
    if dt == jnp.dtype(jnp.float32):
        return [jax.lax.bitcast_convert_type(t, jnp.int32)]
    return [t.astype(jnp.int32)]


def _join_planes(planes: Sequence[jax.Array], dtype) -> jax.Array:
    """Inverse of _split_planes (bit-exact; narrow ints wrap like an
    ordinary astype round trip, which is the identity on their range)."""
    dt = jnp.dtype(dtype)
    if dt.itemsize == 8:
        pair = jnp.stack([planes[0], planes[1]], axis=-1)
        return jax.lax.bitcast_convert_type(pair, dt)
    if dt == jnp.dtype(jnp.float32):
        return jax.lax.bitcast_convert_type(planes[0], dt)
    return planes[0].astype(dt)


def _fill_planes(fill, dtype) -> Tuple[int, ...]:
    """Static per-plane int32 fill words for a table-dtype fill value."""
    arr = np.zeros(1, dtype=jnp.dtype(dtype).name)
    arr[0] = fill
    if arr.dtype.itemsize == 8:
        lo, hi = arr.view(np.int32)
        return (int(lo), int(hi))
    if arr.dtype == np.float32:
        return (int(arr.view(np.int32)[0]),)
    # narrow ints extend like the _split_planes astype, then wrap to the
    # int32 two's-complement range
    v = int(arr.astype(np.int64)[0])
    return (((v + (1 << 31)) % (1 << 32)) - (1 << 31),)


# --------------------------------------------------------------------------
# scan-mode kernel: LUT slabs stream on grid dim 1, output revisited
# --------------------------------------------------------------------------

def _scan_kernel(n_planes: int, fills: tuple):
    def kernel(idx_ref, planes_ref, out_ref):
        s = pl.program_id(1)
        local = idx_ref[...]                             # [SUB, LANES]
        row = jnp.where(local >= 0, local // LANES, -1)
        lane = jnp.where(local >= 0, local % LANES, 0)
        accs = [jnp.where(s == 0,
                          jnp.full((SUB, LANES), fills[p], jnp.int32),
                          out_ref[p]) for p in range(n_planes)]
        base = s * SLAB_ROWS
        for r in range(SLAB_ROWS):
            hit = row == base + r
            for p in range(n_planes):
                src = planes_ref[p, r, :]                # [LANES]
                g = jnp.take_along_axis(
                    jnp.broadcast_to(src[None, :], (SUB, LANES)), lane,
                    axis=1)
                accs[p] = jnp.where(hit, g, accs[p])
        for p in range(n_planes):
            out_ref[p] = accs[p]
    return kernel


def _scan_gather_planes(idx32: jax.Array, planes: jax.Array,
                        fills: tuple, interpret: bool) -> jax.Array:
    """idx32 [n_pad] int32 (pad/miss = -1), planes [P, W_pad] int32 ->
    gathered [P, n_pad] int32."""
    P, W = planes.shape
    n = idx32.shape[0]
    nb, n_slabs = n // TILE, W // SLAB
    out = pl.pallas_call(
        _scan_kernel(P, fills),
        grid=(nb, n_slabs),
        in_specs=[
            pl.BlockSpec((SUB, LANES), lambda i, s: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, SLAB_ROWS, LANES), lambda i, s: (0, s, 0),
                         memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((P, SUB, LANES), lambda i, s: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((P, nb * SUB, LANES), jnp.int32),
        interpret=interpret,
    )(idx32.reshape(nb * SUB, LANES),
      planes.reshape(P, W // LANES, LANES))
    return out.reshape(P, n)


# --------------------------------------------------------------------------
# windowed-mode kernel: per-tile window block via scalar prefetch
# --------------------------------------------------------------------------

def _window_kernel(n_planes: int, fills: tuple):
    """Each tile resolves against TWO adjacent WIN blocks (its minimum
    index's aligned window plus the next), so alignment never causes an
    escape — only a tile whose true key span exceeds WIN does."""
    def kernel(base_ref, idx_ref, lo_win_ref, hi_win_ref, out_ref,
               esc_ref):
        i = pl.program_id(0)
        local = idx_ref[...]
        base = base_ref[i] * WIN               # lo window element offset
        rel = jnp.where(local >= 0, local - base, -1)
        in_win = (rel >= 0) & (rel < 2 * WIN)
        row = jnp.where(in_win, rel // LANES, -1)
        lane = jnp.where(in_win, rel % LANES, 0)
        esc_ref[0, 0] = jnp.sum(
            ((local >= 0) & ~in_win).astype(jnp.int32)).astype(jnp.int32)
        accs = [jnp.full((SUB, LANES), fills[p], jnp.int32)
                for p in range(n_planes)]
        for r in range(2 * WIN_ROWS):
            hit = row == r
            win_ref = lo_win_ref if r < WIN_ROWS else hi_win_ref
            for p in range(n_planes):
                src = win_ref[p, r % WIN_ROWS, :]
                g = jnp.take_along_axis(
                    jnp.broadcast_to(src[None, :], (SUB, LANES)), lane,
                    axis=1)
                accs[p] = jnp.where(hit, g, accs[p])
        for p in range(n_planes):
            out_ref[p] = accs[p]
    return kernel


def _window_gather_planes(idx32: jax.Array, base_blocks: jax.Array,
                          planes: jax.Array, fills: tuple,
                          interpret: bool):
    """idx32 [n_pad] int32 (miss = -1), base_blocks [nb] int32 (per-tile
    WIN-block index, <= n_blocks - 2), planes [P, W_pad] int32 ->
    ([P, n_pad] int32, per-tile escape counts [nb])."""
    P, W = planes.shape
    n = idx32.shape[0]
    nb = n // TILE
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((SUB, LANES), lambda i, base: (i, 0)),
            pl.BlockSpec((P, WIN_ROWS, LANES),
                         lambda i, base: (0, base[i], 0)),
            pl.BlockSpec((P, WIN_ROWS, LANES),
                         lambda i, base: (0, base[i] + 1, 0))],
        out_specs=[
            pl.BlockSpec((P, SUB, LANES), lambda i, base: (0, i, 0)),
            pl.BlockSpec((1, 1), lambda i, base: (i, 0),
                         memory_space=pltpu.SMEM)])
    reshaped = planes.reshape(P, W // LANES, LANES)
    out, esc = pl.pallas_call(
        _window_kernel(P, fills),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((P, nb * SUB, LANES), jnp.int32),
                   jax.ShapeDtypeStruct((nb, 1), jnp.int32)],
        interpret=interpret,
    )(base_blocks, idx32.reshape(nb * SUB, LANES), reshaped, reshaped)
    return out.reshape(P, n), esc.reshape(nb)


# --------------------------------------------------------------------------
# public wrappers (usable inside surrounding jits; all shapes static)
# --------------------------------------------------------------------------

def _sanitize_idx(idx: jax.Array, limit: int) -> jax.Array:
    """Clamp to the fill contract: anything outside [0, limit) becomes
    the -1 miss sentinel BEFORE the int32 narrowing (a wild int64 index
    must not wrap into a valid row)."""
    ok = (idx >= 0) & (idx < limit)
    return jnp.where(ok, idx, -1).astype(jnp.int32)


def _pad_to(x: jax.Array, mult: int, value):
    pad = (-x.shape[-1]) % mult
    if pad == 0:
        return x
    width = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, width, constant_values=value)


def gather_supported(tables, n_rows: Optional[int] = None,
                     max_elems: int = SCAN_MAX_ELEMS) -> bool:
    """Shape gate shared by every call site's auto-fallback."""
    if not tables or not supports_tables(tables):
        return False
    w = tables[0].shape[0]
    if any(t.shape[0] != w for t in tables) or w > max_elems:
        return False
    return True


def _xla_gather(tables, idx, fills):
    """The fallback (and the parity reference): clip-free take with the
    same miss-fill contract as the kernels."""
    w = tables[0].shape[0]
    ok = (idx >= 0) & (idx < w)
    idx_c = jnp.clip(idx, 0, w - 1)
    return [jnp.where(ok, jnp.take(t, idx_c, axis=0),
                      jnp.asarray(f, dtype=t.dtype))
            for t, f in zip(tables, fills)]


def gather_columns(tables, idx, fills=None, *, mode: str = "off"):
    """Fused multi-table gather: out[t][i] = tables[t][idx[i]] when
    0 <= idx[i] < W, else fills[t].  Bit-exact vs the jnp.take path;
    falls back to it when mode is 'off' or the shape gate fails.
    `mode` and all shapes must be static (call under jit is fine).

    This is also the SHARD-LOCAL entry point: inside a shard_map body
    (the mesh-partitioned join's per-chip probe) every shape it sees is
    the per-shard local shape, so the kernel gathers against the 1/N
    table slice resident on its own chip — no cross-chip traffic."""
    tables = list(tables)
    if fills is None:
        fills = [0] * len(tables)
    if mode == "off" or not gather_supported(tables):
        return _xla_gather(tables, idx, fills)
    interpret = mode == "interpret"
    w = tables[0].shape[0]
    n = idx.shape[0]
    idx32 = _pad_to(_sanitize_idx(idx, w), TILE, -1)

    # split every table into int32 planes, group into VMEM-sized calls
    plane_list: List[jax.Array] = []
    plane_fills: List[int] = []
    spans: List[Tuple[int, int, object]] = []   # (start, count, dtype)
    for t, f in zip(tables, fills):
        ps = _split_planes(t)
        spans.append((len(plane_list), len(ps), t.dtype))
        plane_list.extend(_pad_to(p, SLAB, 0) for p in ps)
        plane_fills.extend(_fill_planes(f, t.dtype))

    gathered: List[jax.Array] = []
    for g0 in range(0, len(plane_list), MAX_PLANES):
        group = plane_list[g0:g0 + MAX_PLANES]
        gf = tuple(plane_fills[g0:g0 + MAX_PLANES])
        out = _scan_gather_planes(idx32, jnp.stack(group), gf, interpret)
        gathered.extend(out[p] for p in range(len(group)))

    results = []
    for start, count, dtype in spans:
        results.append(_join_planes(gathered[start:start + count],
                                    dtype)[:n])
    return results


def window_base_blocks(idx32: jax.Array, n_blocks: int) -> jax.Array:
    """Per-(8,128)-tile window choice: the tile's minimum in-range index
    rounded down to a WIN block (computed in XLA, prefetched as scalars
    so the BlockSpec index_map can steer the window DMA).  Clipped to
    n_blocks - 2 because the kernel fetches base and base + 1."""
    nb = idx32.shape[0] // TILE
    tiles = idx32.reshape(nb, TILE)
    sentinel = jnp.int32(2147483647)
    lo = jnp.min(jnp.where(tiles >= 0, tiles, sentinel), axis=1)
    return jnp.clip(lo // WIN, 0, max(n_blocks - 2, 0)).astype(jnp.int32)


def prepare_word_planes(lut: jax.Array) -> jax.Array:
    """One-time prep of a value-packed LUT for gather_word_windowed:
    int32 planes, padded to whole windows (at least two — the kernel
    always fetches a pair).  The chunked driver calls this ONCE per
    pinned LUT so the per-chunk program only streams the windows it
    touches (re-splitting per chunk would re-read the whole domain-sized
    table every chunk)."""
    planes = [_pad_to(p, WIN, 0) for p in _split_planes(lut)]
    if planes[0].shape[0] < 2 * WIN:
        planes = [_pad_to(p, 2 * WIN, 0) for p in planes]
    return jnp.stack(planes)


def gather_word_windowed(planes: jax.Array, idx, word_dtype: str,
                         *, mode: str):
    """Windowed single-word gather off prepared planes (see
    prepare_word_planes): returns (words int64, escaped int64) where
    escaped counts in-range indices that fell outside their tile's
    window — those rows come back as 0 (the packed-LUT miss word) and
    the CALLER MUST rerun via its escape machinery when escaped > 0.
    `word_dtype` is the original LUT dtype (static)."""
    P, W = planes.shape
    n = idx.shape[0]
    idx32 = _pad_to(_sanitize_idx(idx, W), TILE, -1)
    base = window_base_blocks(idx32, W // WIN)
    fills = _fill_planes(0, word_dtype)
    out, esc = _window_gather_planes(idx32, base, planes, fills,
                                     mode == "interpret")
    word = _join_planes([out[p] for p in range(P)],
                        word_dtype)[:n].astype(jnp.int64)
    return word, jnp.sum(esc.astype(jnp.int64))


# --------------------------------------------------------------------------
# pre-jitted, compile-recorded entry points. Inside an executor kernel the
# ENCLOSING jit owns the compile (the recorder stays silent under an open
# trace), so these exist for the eager boundary: the gather microbench and
# any ad-hoc top-level kernel use route their XLA compiles through the
# central recorder (exec/profiler.py) like every other jit site.
# --------------------------------------------------------------------------

from ..exec.profiler import instrument as _instrument  # noqa: E402

gather_columns_jit = _instrument(
    jax.jit(gather_columns, static_argnames=("fills", "mode")),
    site="pallas_gather.gather_columns")
gather_word_windowed_jit = _instrument(
    jax.jit(gather_word_windowed, static_argnames=("word_dtype", "mode")),
    site="pallas_gather.gather_word_windowed")
