"""Join kernels.

Reference: Trino's lookup join — HashBuilderOperator fills a PagesIndex and
builds a JoinHash; LookupJoinOperator probes it per page
(operator/join/unspilled/HashBuilderOperator.java:48,
unspilled/LookupJoinOperator.java:41, PageJoiner.java:138).

Two build structures, chosen like BigintGroupByHash vs FlatGroupByHash
(GroupByHash.java:82-93), measured on v5e via the tunnel at 60M probe /
15M build rows:

- **dense-domain LUT** (single integer key, bounded domain known from
  connector stats — every TPC-H/DS surrogate key): build rows scatter into
  a dense `domain`-sized table (unique-index scatter, 0.2s) and each probe
  is ONE gather (0.9s). This is the BigintGroupByHash analog and the fast
  path for fact-dimension joins.
- **sorted-array + binary search** (general fallback): `lax.sort` of the
  build (0.2s at 15M — TPU sorts are fast) and `searchsorted` probes.
  searchsorted lowers to ~24 sequential gather rounds (30s at 60M probes)
  — usable for small/medium probes, pathological at scale, hence the LUT.
- **hybrid hash** (sparse key domains the dense LUT refuses): the VMEM
  hash-table kernel (`ops/pallas_hash.py`) builds a key -> min(row_id)
  table (duplicates detected as inserted > occupied) and the probe walks
  each linear chain with MAX_PROBES rounds of fused plane gathers —
  bounded chains, so exhausting them is a definitive miss. Sits in the
  unique-build ladder ahead of this fallback and carries semi/anti
  membership joins; a build past the table's load cap degrades
  partition-by-partition through the spill tier's radix fanout
  (`Executor.try_hash_join`).

Output-row mapping in the expansion kernels uses scatter + cummax
(associative scan) instead of a second searchsorted for the same reason.

Duplicate-build joins run the two-pass device expansion (join_expand)
under a static output bound with grow-and-retry on overflow (the
"conservative upper bounds" mitigation from SURVEY.md §7 hard part 1).

Multi-column equi-keys are packed into one int64 by the planner (key
columns are bounded by table cardinalities, known from connector stats);
packed keys use the sorted fallback.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..exec.profiler import recorded_jit

from ..batch import Batch, Column
from . import pallas_gather

_SENTINEL = jnp.iinfo(jnp.int64).max


def _lut_probe(lut: jax.Array, p_idx: jax.Array,
               gather_mode: str) -> jax.Array:
    """One LUT word per probe index — the Pallas tiled-gather kernel
    when enabled and the table is inside its win region, else the XLA
    gather (ops/pallas_gather.py; bit-exact either way)."""
    if gather_mode != "off" and pallas_gather.gather_supported([lut]):
        return pallas_gather.gather_columns([lut], p_idx,
                                            mode=gather_mode)[0]
    return lut[p_idx]


def _combined_key(batch: Batch, key_indices: tuple) -> Tuple[jax.Array,
                                                             jax.Array]:
    """(key, key_valid) as int64. Multi-column keys pack 32 bits per
    trailing column (key columns are table keys bounded well below 2^31;
    the executor validates ranges host-side before taking this path)."""
    col = batch.columns[key_indices[0]]
    key = col.data.astype(jnp.int64)
    valid = col.valid
    for ki in key_indices[1:]:
        c = batch.columns[ki]
        key = key * (1 << 32) + c.data.astype(jnp.int64)
        valid = valid & c.valid
    return key, valid


def _cummax(x: jax.Array) -> jax.Array:
    return jax.lax.associative_scan(jnp.maximum, x)


def _dense_row_lut(key: jax.Array, ok: jax.Array, domain: int):
    """Scatter build-row indices into a dense key->row table.

    Returns (lut[domain+1] int32, dup_count). Slot `domain` is the
    dead/invalid sink. -1 = no build row for that key. Duplicates are
    detected by reading back: an overwritten row's slot holds a different
    row index."""
    n = key.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    idx = jnp.where(ok, jnp.clip(key, 0, domain - 1), domain)
    lut = jnp.full(domain + 1, -1, dtype=jnp.int32)
    lut = lut.at[idx].max(rows, mode="drop")
    readback = lut[idx]
    dup = jnp.sum(ok & (readback != rows))
    return lut, dup


def _out_of_domain(key: jax.Array, ok: jax.Array, domain: int):
    return jnp.any(ok & ((key < 0) | (key >= domain)))


@recorded_jit(static_argnums=(2, 3, 4, 5, 6))
def join_unique_build_dense(probe: Batch, build: Batch, probe_keys: tuple,
                            build_keys: tuple, kind: str, domain: int,
                            gather_mode: str = "off"):
    """Unique-build equi-join via dense LUT: one scatter to build, one
    gather per probe (the BigintGroupByHash-style fast path).

    Random gathers are the whole cost on TPU (~1s per 60M-row column
    through XLA's gather), so the kernel gathers as little as possible:
    the build KEY column is reconstructed from the probe key (equal by
    definition where matched), and all build validity masks pack into ONE
    gathered word instead of one bool gather per column.

    Returns (out_batch, dup_count, oob_count); oob_count > 0 means a
    build key fell outside [0, domain) — the caller's stats were stale
    and it must re-run on the sorted fallback."""
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)
    b_ok = build.live & bk_valid
    oob = jnp.sum(b_ok & ((bk < 0) | (bk >= domain)))
    lut, dup = _dense_row_lut(bk, b_ok, domain)

    p_idx = jnp.where(pk_valid, jnp.clip(pk, 0, domain - 1), domain)
    src = _lut_probe(lut, p_idx, gather_mode)
    matched = (src >= 0) & pk_valid & probe.live & \
        (pk >= 0) & (pk < domain)
    src_c = jnp.clip(src, 0, build.capacity - 1)

    if kind == "semi":
        return probe.with_live(probe.live & matched), dup, oob
    if kind == "anti":
        return probe.with_live(probe.live & ~matched), dup, oob
    return (_gather_build_payload(probe, build, src_c, matched, pk,
                                  build_keys, kind, gather_mode),
            dup, oob)


def _gather_build_payload(probe: Batch, build: Batch, src_c, matched, pk,
                          build_keys: tuple, kind: str,
                          gather_mode: str = "off") -> Batch:
    """Per-column build gathers of a dense-LUT probe result (traced
    helper shared by the one-shot and reused-LUT kernels). `src_c` must
    already be clipped to [0, build.capacity).

    With `gather_mode` on, the validity word and every payload column
    ride ONE Pallas multi-table gather: the kernel decomposes each probe
    index once and streams all planes past it (the whole point of the
    tiled-gather kernel — per-index cost no longer scales with the
    payload column count)."""
    bkey = build_keys[0] if len(build_keys) == 1 else None
    pack_valids = len(build.columns) <= 63
    payload = [i for i in range(len(build.columns)) if i != bkey]
    vbits = None
    vword = None
    if pack_valids:
        # validity word: bit i = column i valid (skipping the key column,
        # whose validity IS `matched`)
        vword = jnp.zeros(build.capacity, dtype=jnp.int64)
        for i, col in enumerate(build.columns):
            if i == bkey:
                continue
            vword = vword | (col.valid.astype(jnp.int64) << i)

    gathered = None
    tables = ([vword] if pack_valids else []) + \
        [build.columns[i].data for i in payload]
    if gather_mode != "off" and pack_valids and \
            pallas_gather.gather_supported(tables):
        outs = pallas_gather.gather_columns(tables, src_c,
                                            mode=gather_mode)
        vbits = outs[0]
        gathered = dict(zip(payload, outs[1:]))
    elif pack_valids:
        vbits = vword[src_c]

    build_cols = []
    for i, col in enumerate(build.columns):
        if i == bkey:
            # matched rows' build key == probe key; no gather needed
            build_cols.append(Column(
                data=jnp.where(matched, pk, 0).astype(col.data.dtype),
                valid=matched))
            continue
        valid = ((vbits >> i) & 1).astype(jnp.bool_) if pack_valids \
            else col.valid[src_c]
        data = gathered[i] if gathered is not None else col.data[src_c]
        build_cols.append(Column(data=data, valid=valid & matched))
    live = probe.live & matched if kind == "inner" else probe.live
    return Batch(columns=probe.columns + tuple(build_cols), live=live)


@recorded_jit(static_argnums=(1, 2))
def dense_build_lut(build: Batch, build_keys: tuple, domain: int):
    """Build the dense key->row LUT ONCE for a pinned build side (chunked
    execution reuses it across every probe chunk instead of re-scattering
    per chunk). Returns (lut, dup_count, oob_count) — the caller
    validates dup/oob with a single device fetch at build time, after
    which probes are sync-free."""
    bk, bk_valid = _combined_key(build, build_keys)
    b_ok = build.live & bk_valid
    oob = jnp.sum(b_ok & ((bk < 0) | (bk >= domain)),
                  dtype=jnp.int64)
    lut, dup = _dense_row_lut(bk, b_ok, domain)
    return lut, dup, oob


@recorded_jit(static_argnums=(3, 4, 5, 6))
def dense_join_with_lut(probe: Batch, build: Batch, lut: jax.Array,
                        probe_keys: tuple, build_keys: tuple,
                        kind: str, gather_mode: str = "off") -> Batch:
    """Probe a prebuilt (already-validated) dense LUT: no duplicate /
    out-of-domain checks, no host syncs, no compaction — the chunked
    driver's steady-state join. Output keeps probe capacity with a live
    mask; every tunnel round trip avoided is ~260 ms on this rig."""
    domain = lut.shape[0] - 1
    pk, pk_valid = _combined_key(probe, probe_keys)
    p_idx = jnp.where(pk_valid, jnp.clip(pk, 0, domain - 1), domain)
    src = _lut_probe(lut, p_idx, gather_mode)
    matched = (src >= 0) & pk_valid & probe.live & \
        (pk >= 0) & (pk < domain)
    if kind == "semi":
        return probe.with_live(probe.live & matched)
    if kind == "anti":
        return probe.with_live(probe.live & ~matched)
    src_c = jnp.clip(src, 0, build.capacity - 1)
    return _gather_build_payload(probe, build, src_c, matched, pk,
                                 build_keys, kind, gather_mode)


@recorded_jit(static_argnums=(2, 3))
def build_lut_chunk(lut: jax.Array, chunk: Batch, key_idx: int,
                    domain: int, start) -> jax.Array:
    """Scatter one build chunk's GLOBAL row ids into a persistent dense
    LUT (streaming-build join, exec/chunked.py): the LUT is domain-sized
    regardless of build row count, so arbitrarily large build sides
    stream through one chunk of HBM.

    Also returns (in-domain valid rows, out-of-domain valid rows) so the
    caller can validate the planner's uniqueness proof at runtime
    (duplicates show up as scattered-rows > occupied-slots; oob keys
    would be silently clipped) without a second kernel per chunk."""
    key = chunk.columns[key_idx]
    ok = chunk.live & key.valid
    in_dom = ok & (key.data >= 0) & (key.data < domain)
    idx = jnp.where(ok, jnp.clip(key.data, 0, domain - 1), domain)
    rows = (jnp.arange(chunk.capacity, dtype=jnp.int64) +
            start).astype(jnp.int32)
    return (lut.at[idx].max(rows, mode="drop"),
            jnp.sum(in_dom, dtype=jnp.int64),
            jnp.sum(ok & ~in_dom, dtype=jnp.int64))


@recorded_jit(static_argnums=(1, 2, 3, 4))
def dense_build_packed_lut(build: Batch, build_keys: tuple, domain: int,
                           meta: tuple, word_dtype: str):
    """Value-packed dense LUT: the build row's PAYLOAD values pack into
    the LUT word itself (bit0 = presence, then per payload column
    `width` value bits offset by `lo` plus one validity bit), so a probe
    is ONE gather total instead of a row-id gather plus one gather per
    payload column. On this backend a 50M-row HBM gather costs ~1s —
    for a 2-payload join the packed form is ~3x fewer gathers.

    meta: ((col_idx, lo, width, val_off, valid_off), ...) — static.
    Returns (lut, expected_rows, oob_rows, occupied_slots); duplicates
    show up as occupied < expected (unique-build violation), validated
    by the caller in one fetch."""
    bk, bk_valid = _combined_key(build, build_keys)
    ok = build.live & bk_valid
    in_dom = ok & (bk >= 0) & (bk < domain)
    word = jnp.ones(build.capacity, dtype=jnp.int64)      # presence bit
    for col_idx, lo, width, val_off, valid_off in meta:
        col = build.columns[col_idx]
        v = (col.data.astype(jnp.int64) - lo) & ((1 << width) - 1)
        word = word | (v << val_off) | \
            (col.valid.astype(jnp.int64) << valid_off)
    idx = jnp.where(in_dom, jnp.clip(bk, 0, domain - 1), domain)
    lut = jnp.zeros(domain + 1, dtype=jnp.dtype(word_dtype))
    lut = lut.at[idx].max(word.astype(lut.dtype), mode="drop")
    occupied = jnp.sum((lut[:domain] != 0).astype(jnp.int64))
    return (lut, jnp.sum(in_dom, dtype=jnp.int64),
            jnp.sum(ok & ~in_dom, dtype=jnp.int64), occupied)


def dense_join_packed_windowed(probe: Batch, lut: jax.Array,
                               probe_keys: tuple, meta: tuple, bkey: int,
                               out_dtypes: tuple, kind: str, window: int,
                               word_dtype: str = None,
                               gather_mode: str = "off",
                               lut_planes=None):
    """dense_join_packed for NEAR-SORTED probe keys: gathers from a
    dynamic window slice of the LUT instead of the full table — the
    chunk's key span stays cache-resident, measured ~1.9x faster than
    the full-table gather on v5e. `window` is a static size from the
    decision cache (a previous run's measured max span, padded).

    With `gather_mode` on and `lut_planes` prepared (one-time,
    pallas_gather.prepare_word_planes), the probe instead runs the
    Pallas windowed kernel: each (8,128) index tile fetches its own
    WIN-sized pair of LUT blocks by scalar-prefetched block index and
    resolves all 1024 probes in-register — per-probe cost independent of
    both table size and chunk key span.  Kernel escapes (a tile spanning
    more than WIN entries) land in the same `escaped` counter, so the
    driver's existing rerun-plain machinery covers both paths.

    Returns (batch, escaped, span): `escaped` counts in-domain keys that
    fell OUTSIDE the window — the caller MUST check it is zero at the
    end of the chunk loop and rerun the plain program otherwise (rows
    outside the window come back unmatched); `span` is the chunk's true
    key extent for re-recording."""
    domain = lut.shape[0] - 1
    window = min(window, domain + 1)
    pk, pk_valid = _combined_key(probe, probe_keys)
    ok_rows = pk_valid & probe.live & (pk >= 0) & (pk < domain)
    big = jnp.int64(domain)
    lo = jnp.min(jnp.where(ok_rows, pk, big))
    hi = jnp.max(jnp.where(ok_rows, pk, jnp.int64(-1)))
    span = jnp.maximum(hi - lo + 1, 0)
    if gather_mode != "off" and lut_planes is not None and \
            domain + 1 <= pallas_gather.MAX_WINDOWED_ELEMS:
        word, escaped = pallas_gather.gather_word_windowed(
            lut_planes, jnp.where(ok_rows, pk, jnp.int64(-1)),
            word_dtype or str(lut.dtype), mode=gather_mode)
        matched = (word != 0) & ok_rows
    else:
        w0 = jnp.clip(lo, 0, jnp.maximum(domain + 1 - window, 0))
        win = jax.lax.dynamic_slice(lut, (w0,), (window,))
        local = pk - w0
        in_win = (local >= 0) & (local < window)
        word = win[jnp.clip(local, 0, window - 1)].astype(jnp.int64)
        matched = (word != 0) & ok_rows & in_win
        escaped = jnp.sum(ok_rows & ~in_win, dtype=jnp.int64)
    if kind == "semi":
        return probe.with_live(probe.live & matched), escaped, span
    if kind == "anti":
        return probe.with_live(probe.live & ~matched), escaped, span
    by_idx = {m[0]: m for m in meta}
    build_cols = []
    for i, dt in enumerate(out_dtypes):
        dtype = jnp.dtype(dt)
        if i == bkey:
            build_cols.append(Column(
                data=jnp.where(matched, pk, 0).astype(dtype),
                valid=matched))
            continue
        col_idx, lo_v, width, val_off, valid_off = by_idx[i]
        raw = (word >> val_off) & ((1 << width) - 1)
        build_cols.append(Column(
            data=(raw + lo_v).astype(dtype),
            valid=(((word >> valid_off) & 1) != 0) & matched))
    live = probe.live & matched if kind == "inner" else probe.live
    return (Batch(columns=probe.columns + tuple(build_cols), live=live),
            escaped, span)


def compact_live(batch: Batch, cap: int):
    """In-jit compaction to a STATIC capacity (decision-cached measured
    live count, padded). Returns (batch, overflow) where overflow counts
    live rows beyond `cap` — the caller must check it is zero at the end
    of the chunk loop and rerun unfused otherwise."""
    n = batch.capacity
    idx = jnp.nonzero(batch.live, size=cap, fill_value=n)[0]
    ok = idx < n
    idxc = jnp.clip(idx, 0, n - 1)
    cols = tuple(Column(c.data[idxc], c.valid[idxc] & ok)
                 for c in batch.columns)
    overflow = jnp.sum(batch.live, dtype=jnp.int64) - \
        jnp.sum(ok, dtype=jnp.int64)
    return Batch(cols, ok), overflow


@recorded_jit(static_argnums=(2, 3, 4, 5, 6, 7))
def dense_join_packed(probe: Batch, lut: jax.Array, probe_keys: tuple,
                      meta: tuple, bkey: int, out_dtypes: tuple,
                      kind: str, gather_mode: str = "off") -> Batch:
    """Probe a value-packed LUT (see dense_build_packed_lut): one gather
    yields presence + every payload value. Build columns reconstruct in
    the build's output order; the key column reconstructs from the probe
    key (equal where matched). Sync-free, no compaction — the fused
    chunk pipeline's join step."""
    domain = lut.shape[0] - 1
    pk, pk_valid = _combined_key(probe, probe_keys)
    p_idx = jnp.where(pk_valid, jnp.clip(pk, 0, domain - 1), domain)
    word = _lut_probe(lut, p_idx, gather_mode).astype(jnp.int64)
    matched = (word != 0) & pk_valid & probe.live & \
        (pk >= 0) & (pk < domain)
    if kind == "semi":
        return probe.with_live(probe.live & matched)
    if kind == "anti":
        return probe.with_live(probe.live & ~matched)
    by_idx = {m[0]: m for m in meta}
    build_cols = []
    for i, dt in enumerate(out_dtypes):
        dtype = jnp.dtype(dt)
        if i == bkey:
            build_cols.append(Column(
                data=jnp.where(matched, pk, 0).astype(dtype),
                valid=matched))
            continue
        col_idx, lo, width, val_off, valid_off = by_idx[i]
        raw = (word >> val_off) & ((1 << width) - 1)
        build_cols.append(Column(
            data=(raw + lo).astype(dtype),
            valid=(((word >> valid_off) & 1) != 0) & matched))
    live = probe.live & matched if kind == "inner" else probe.live
    return Batch(columns=probe.columns + tuple(build_cols), live=live)


@recorded_jit(static_argnums=(2, 3, 4))
def dense_probe(probe: Batch, build: Batch, probe_keys: tuple,
                build_keys: tuple, domain: int):
    """Phase 1 of the two-phase dense join: LUT build + probe lookup
    only. Returns (src row indices, matched mask, dup, oob, match
    count) — ONE gather at probe capacity; the caller decides whether
    to compact before paying the per-column build gathers (phase 2)."""
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)
    b_ok = build.live & bk_valid
    oob = jnp.sum(b_ok & ((bk < 0) | (bk >= domain)))
    lut, dup = _dense_row_lut(bk, b_ok, domain)
    p_idx = jnp.where(pk_valid, jnp.clip(pk, 0, domain - 1), domain)
    src = lut[p_idx]
    matched = (src >= 0) & pk_valid & probe.live & \
        (pk >= 0) & (pk < domain)
    return src, matched, dup, oob, jnp.sum(matched, dtype=jnp.int64)


@recorded_jit(static_argnums=(4, 5, 6, 7))
def dense_join_compacted(probe: Batch, src: jax.Array,
                         matched: jax.Array, build: Batch,
                         probe_keys: tuple, build_keys: tuple,
                         new_capacity: int,
                         gather_mode: str = "off") -> Batch:
    """Phase 2 (selective inner join): compact matched probe rows first
    (argsort of the match mask), then gather probe AND build payload
    columns at the compacted capacity only. For a 60M-capacity probe
    with a few-percent match rate this replaces several 60M-row gathers
    with ~matched-size ones — gathers are the whole cost of the dense
    join on TPU.

    `matched` MUST be phase 1's mask: it carries the key-validity and
    domain-range checks (src >= 0 alone is not sufficient — the LUT's
    dead-row sink slot holds a real row id, so NULL-key probes would
    join spuriously and overflow new_capacity)."""
    order = jnp.argsort(~matched, stable=True)[:new_capacity]
    live = matched[order]
    src_c = jnp.clip(src[order], 0, build.capacity - 1)

    cols = []
    for c in probe.columns:
        cols.append(Column(data=c.data[order], valid=c.valid[order]))
    bkey = build_keys[0] if len(build_keys) == 1 else None
    pack_valids = len(build.columns) <= 63
    payload = [i for i in range(len(build.columns)) if i != bkey]
    vbits = None
    vword = None
    gathered = None
    if pack_valids:
        vword = jnp.zeros(build.capacity, dtype=jnp.int64)
        for i, col in enumerate(build.columns):
            if i == bkey:
                continue
            vword = vword | (col.valid.astype(jnp.int64) << i)
        tables = [vword] + [build.columns[i].data for i in payload]
        if gather_mode != "off" and \
                pallas_gather.gather_supported(tables):
            outs = pallas_gather.gather_columns(tables, src_c,
                                                mode=gather_mode)
            vbits = outs[0]
            gathered = dict(zip(payload, outs[1:]))
        else:
            vbits = vword[src_c]
    for i, col in enumerate(build.columns):
        if i == bkey:
            # matched rows' build key == probe key (single-key joins)
            pk = probe.columns[probe_keys[0]]
            cols.append(Column(
                data=jnp.where(live, pk.data[order], 0).astype(
                    col.data.dtype),
                valid=live))
            continue
        valid = ((vbits >> i) & 1).astype(jnp.bool_) if pack_valids \
            else col.valid[src_c]
        data = gathered[i] if gathered is not None else col.data[src_c]
        cols.append(Column(data=data, valid=valid & live))
    return Batch(columns=tuple(cols), live=live)


def _flood_first(vals: jax.Array, boundary: jax.Array) -> jax.Array:
    """Inclusive segmented scan keeping each segment's FIRST value —
    log-depth elementwise passes, no gathers."""
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, va)
    _, out = jax.lax.associative_scan(combine, (boundary, vals))
    return out


@recorded_jit(static_argnums=(2, 3, 4))
def join_unique_build_merge(probe: Batch, build: Batch,
                            probe_keys: tuple, build_keys: tuple,
                            kind: str):
    """Unique-build equi-join as a sort-merge: concat both sides, ONE
    multi-operand sort by (key, side), then flood each run's build row
    (first in its run) across the run with segmented scans.

    Zero random gathers: the sort network moves every payload column at
    HBM-friendly cost (~0.7s for 67M x 5 operands on v5e) where
    XLA's gather costs ~1.6s PER COLUMN — this kernel is why. The output
    batch has capacity probe+build (build slots dead) and is ordered by
    key; callers compact (sort-based, cheap) when live density drops.

    kind: 'inner' | 'left'. Returns (out_batch, dup_count)."""
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)
    m, n = build.capacity, probe.capacity
    b_ok = build.live & bk_valid
    p_ok = probe.live & pk_valid
    key = jnp.concatenate([jnp.where(b_ok, bk, _SENTINEL),
                           jnp.where(p_ok, pk, _SENTINEL)])
    side = jnp.concatenate([jnp.zeros(m, dtype=jnp.int8),
                            jnp.ones(n, dtype=jnp.int8)])

    bkey = build_keys[0] if len(build_keys) == 1 else None
    operands = [key, side]
    # probe payloads ride the sort (zeros in build slots)
    p_slots = []
    for col in probe.columns:
        operands.append(jnp.concatenate([
            jnp.zeros(m, dtype=col.data.dtype), col.data]))
        p_slots.append(len(operands) - 1)
    pvw = jnp.zeros(n, dtype=jnp.int64)
    for i, col in enumerate(probe.columns):
        pvw = pvw | (col.valid.astype(jnp.int64) << i)
    operands.append(jnp.concatenate([jnp.zeros(m, dtype=jnp.int64),
                                     pvw]))
    pvw_slot = len(operands) - 1
    # build payloads (key column reconstructed from the run key)
    b_slots = {}
    for i, col in enumerate(build.columns):
        if i == bkey:
            continue
        operands.append(jnp.concatenate([
            col.data, jnp.zeros(n, dtype=col.data.dtype)]))
        b_slots[i] = len(operands) - 1
    bvw = jnp.zeros(m, dtype=jnp.int64)
    for i, col in enumerate(build.columns):
        bvw = bvw | (col.valid.astype(jnp.int64) << i)
    operands.append(jnp.concatenate([bvw, jnp.zeros(n, dtype=jnp.int64)]))
    bvw_slot = len(operands) - 1
    operands.append(jnp.concatenate([jnp.zeros(m, dtype=jnp.bool_),
                                     probe.live]))

    out = jax.lax.sort(tuple(operands), num_keys=2)
    skey, sside = out[0], out[1]
    plive = out[-1]
    N = m + n
    pos = jnp.arange(N)
    boundary = (pos == 0) | (skey != jnp.roll(skey, 1))
    is_build = (sside == 0) & (skey != _SENTINEL)
    # a build row not at its run start follows another build row of the
    # same key (side sorts build first) — the uniqueness violation
    dup = jnp.sum(is_build & ~boundary)
    has_build = _flood_first(is_build & boundary, boundary)
    is_probe = sside == 1
    matched = is_probe & has_build & (skey != _SENTINEL)

    spvw = out[pvw_slot]
    sbvw = _flood_first(out[bvw_slot], boundary)
    cols = []
    for i, col in enumerate(probe.columns):
        cols.append(Column(
            data=out[p_slots[i]],
            valid=((spvw >> i) & 1).astype(jnp.bool_) & is_probe))
    for i, col in enumerate(build.columns):
        if i == bkey:
            cols.append(Column(
                data=jnp.where(matched, skey, 0).astype(col.data.dtype),
                valid=matched))
            continue
        cols.append(Column(
            data=_flood_first(out[b_slots[i]], boundary),
            valid=((sbvw >> i) & 1).astype(jnp.bool_) & matched))
    live = plive & (matched if kind == "inner" else is_probe)
    return Batch(columns=tuple(cols), live=live), dup


@recorded_jit(static_argnums=(2, 3, 4))
def join_unique_build(probe: Batch, build: Batch, probe_keys: tuple,
                      build_keys: tuple, kind: str):
    """Equi-join where the build side is unique on its key.

    kind: 'inner' | 'left' | 'semi' | 'anti'.
    Returns (out_batch, dup_count) where dup_count>0 means the uniqueness
    assumption failed and the caller must re-run on the fallback path.
    - inner/left: output = probe columns ++ build columns (gathered)
    - semi/anti: output = probe columns, live-mask filtered
    """
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)

    # dead or NULL-keyed build rows sort to +inf and never match
    bk_eff = jnp.where(build.live & bk_valid, bk, _SENTINEL)
    n_build = build.capacity
    sorted_keys, order = jax.lax.sort((bk_eff, jnp.arange(
        n_build, dtype=jnp.int32)), num_keys=1)

    dup = jnp.sum((sorted_keys[1:] == sorted_keys[:-1]) &
                  (sorted_keys[1:] != _SENTINEL))

    pos = jnp.searchsorted(sorted_keys, pk)
    pos_c = jnp.clip(pos, 0, n_build - 1)
    matched = (sorted_keys[pos_c] == pk) & pk_valid & (pk != _SENTINEL)
    src = order[pos_c]

    if kind == "semi":
        return probe.with_live(probe.live & matched), dup
    if kind == "anti":
        # EXISTS-complement: a NULL probe key matches nothing, so the row
        # survives NOT EXISTS. NOT IN's null-awareness is the planner's
        # job (IS NOT NULL pre-filter + executor build-null check).
        return probe.with_live(probe.live & ~matched), dup

    build_cols = []
    for col in build.columns:
        data = col.data[src]
        valid = col.valid[src] & matched
        build_cols.append(Column(data=data, valid=valid))
    if kind == "inner":
        live = probe.live & matched
    else:  # left
        live = probe.live
    return Batch(columns=probe.columns + tuple(build_cols), live=live), dup


def _expand_map(out_counts: jax.Array, out_capacity: int):
    """Output row j -> (probe_row, within-run offset) without binary
    search: scatter each probe row's index at its output start, then a
    cummax scan floods it across the run (associative scan = log rounds
    of elementwise max, no gathers)."""
    n = out_counts.shape[0]
    cum = jnp.cumsum(out_counts)
    total = cum[n - 1]
    starts = cum - out_counts
    has = out_counts > 0
    idx = jnp.where(has & (starts < out_capacity), starts, out_capacity)
    seed = jnp.zeros(out_capacity + 1, dtype=jnp.int32)
    seed = seed.at[idx].max(jnp.arange(n, dtype=jnp.int32) + 1,
                            mode="drop")
    probe_row = _cummax(seed[:out_capacity]) - 1
    probe_row_c = jnp.clip(probe_row, 0, n - 1)
    j = jnp.arange(out_capacity, dtype=cum.dtype)
    out_live = (j < total) & (probe_row >= 0)
    within = j - starts[probe_row_c]
    return probe_row_c, within, out_live, total


def _dense_run_luts(sorted_keys: jax.Array, domain: int):
    """(lo, count) per key from a sorted build — two unique-index
    scatters; absent keys read back count 0."""
    n = sorted_keys.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    validk = sorted_keys != _SENTINEL
    in_dom = validk & (sorted_keys >= 0) & (sorted_keys < domain)
    boundary = in_dom & ((pos == 0) |
                         (sorted_keys != jnp.roll(sorted_keys, 1)))
    run_end = in_dom & ((pos == n - 1) |
                        (jnp.roll(sorted_keys, -1) != sorted_keys))
    key_c = jnp.clip(sorted_keys, 0, domain - 1).astype(jnp.int64)
    lo_lut = jnp.zeros(domain + 1, dtype=jnp.int32)
    lo_lut = lo_lut.at[jnp.where(boundary, key_c, domain)].max(
        pos, mode="drop")
    lo_of_row = lo_lut[key_c]
    cnt_lut = jnp.zeros(domain + 1, dtype=jnp.int32)
    cnt_lut = cnt_lut.at[jnp.where(run_end, key_c, domain)].max(
        pos - lo_of_row + 1, mode="drop")
    oob = jnp.sum(validk & ~in_dom)
    return lo_lut, cnt_lut, oob


def _probe_runs(probe: Batch, build: Batch, probe_keys: tuple,
                build_keys: tuple, domain):
    """Per-probe-row (lo, count) of the matching build run, plus the
    build sort order. domain None = sorted+searchsorted fallback."""
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)
    n_build = build.capacity
    bk_eff = jnp.where(build.live & bk_valid, bk, _SENTINEL)
    sorted_keys, order = jax.lax.sort(
        (bk_eff, jnp.arange(n_build, dtype=jnp.int32)), num_keys=1)
    pk_ok = probe.live & pk_valid & (pk != _SENTINEL)
    if domain is None:
        lo = jnp.searchsorted(sorted_keys, pk, side="left")
        hi = jnp.searchsorted(sorted_keys, pk, side="right")
        counts = jnp.where(pk_ok, hi - lo, 0)
        oob = jnp.zeros((), dtype=jnp.int64)
    else:
        lo_lut, cnt_lut, oob = _dense_run_luts(sorted_keys, domain)
        ok = pk_ok & (pk >= 0) & (pk < domain)
        p_idx = jnp.where(ok, pk, domain)
        # the sink slot collects non-run-end scatter garbage; only
        # in-domain live probes may read real counts
        lo = jnp.where(ok, lo_lut[p_idx], 0).astype(jnp.int64)
        counts = jnp.where(ok, cnt_lut[p_idx], 0).astype(jnp.int64)
    return lo, counts, order, pk_ok, oob


@recorded_jit(static_argnums=(2, 3, 4, 5, 6))
def join_expand(probe: Batch, build: Batch, probe_keys: tuple,
                build_keys: tuple, kind: str, out_capacity: int,
                domain=None):
    """Equi-join with arbitrary build-side multiplicity (1:N fan-out),
    fully on device.

    Two-pass expansion (the TPU answer to LookupJoinOperator's variable
    JoinProbe fan-out, operator/join/unspilled/PageJoiner.java:138):
    1. per-probe-row match runs (dense LUTs when `domain` is given, else
       sorted build + searchsorted);
    2. output row j maps to its probe row by scatter+cummax and to its
       build row by offset within the run.

    Returns (out_batch, total_rows, oob); total_rows > out_capacity means
    the static bound overflowed and the caller must grow and retry; oob >
    0 means build keys fell outside the dense domain and the caller must
    re-run with domain=None. kind: 'inner' | 'left'.
    """
    n_build = build.capacity
    lo, counts, order, pk_ok, oob = _probe_runs(
        probe, build, probe_keys, build_keys, domain)
    if kind == "left":
        out_counts = jnp.maximum(counts, probe.live.astype(counts.dtype))
    else:
        out_counts = counts
    probe_row_c, within, out_live, total = _expand_map(out_counts,
                                                       out_capacity)
    matched = out_live & (within < counts[probe_row_c])
    build_row = order[jnp.clip(lo[probe_row_c] + within, 0, n_build - 1)]

    out_cols = []
    for col in probe.columns:
        out_cols.append(Column(data=col.data[probe_row_c],
                               valid=col.valid[probe_row_c] & out_live))
    for col in build.columns:
        out_cols.append(Column(data=col.data[build_row],
                               valid=col.valid[build_row] & matched))
    return Batch(columns=tuple(out_cols), live=out_live), total, oob


@recorded_jit(static_argnums=(2, 3, 4, 5, 6))
def join_mark(probe: Batch, build: Batch, probe_keys: tuple,
              build_keys: tuple, residual, out_capacity: int,
              domain=None):
    """Mark join: per probe row, does ANY build row match the equi keys AND
    the residual predicate? Powers semi/anti joins with non-equi correlated
    conditions (TPC-H q21's l2.l_suppkey <> l1.l_suppkey), the role of
    Trino's JoinFilterFunction on semi joins
    (sql/gen/JoinFilterFunctionCompiler.java).

    Same two-pass expansion as join_expand; the residual is evaluated over
    the expanded pair batch (probe columns ++ build columns), then reduced
    back per probe row with a cumulative-count window.

    Returns (mark_bool_per_probe_row, total_pairs, oob). total_pairs >
    out_capacity means the expansion overflowed; caller grows and retries.
    """
    from .project import filter_mask

    n_build = build.capacity
    lo, counts, order, pk_ok, oob = _probe_runs(
        probe, build, probe_keys, build_keys, domain)
    cum = jnp.cumsum(counts)
    probe_row_c, within, out_live, total = _expand_map(counts,
                                                       out_capacity)
    pair_live = out_live & (within < counts[probe_row_c])
    build_row = order[jnp.clip(lo[probe_row_c] + within, 0, n_build - 1)]

    pair_cols = []
    for col in probe.columns:
        pair_cols.append(Column(data=col.data[probe_row_c],
                                valid=col.valid[probe_row_c] & pair_live))
    for col in build.columns:
        pair_cols.append(Column(data=col.data[build_row],
                                valid=col.valid[build_row] & pair_live))
    pairs = Batch(columns=tuple(pair_cols), live=pair_live)
    ok = filter_mask(residual, pairs) & pair_live if residual is not None \
        else pair_live

    # per-probe-row "any ok": windowed sum over the cumulative ok counts
    cs = jnp.cumsum(ok.astype(jnp.int64))
    start = jnp.clip(jnp.minimum(cum - counts, out_capacity - 1), 0, None)
    end = jnp.clip(cum - 1, 0, out_capacity - 1)
    upto_end = cs[end]
    before_start = jnp.where(start > 0, cs[jnp.clip(start - 1, 0,
                                                    out_capacity - 1)], 0)
    any_ok = (counts > 0) & ((upto_end - before_start) > 0)
    return any_ok, total, oob
