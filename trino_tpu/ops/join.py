"""Join kernels.

Reference: Trino's lookup join — HashBuilderOperator fills a PagesIndex and
builds a JoinHash; LookupJoinOperator probes it per page
(operator/join/unspilled/HashBuilderOperator.java:48,
unspilled/LookupJoinOperator.java:41, PageJoiner.java:138).

TPUs lack efficient pointer-chasing, so the build structure is a *sorted key
array* and the probe is a vectorized binary search (`searchsorted`, which
XLA lowers to a fully parallel per-lane search) — exact, static-shape, no
hash collisions (SURVEY.md §7 "GroupBy/Join on TPU").

Unique-build joins (key is a primary key: every TPC-H dimension join) have
fan-out <= 1, so output capacity == probe capacity and everything stays on
device. Duplicate-build joins run the two-pass device expansion
(join_expand) under a static output bound with grow-and-retry on overflow
(the "conservative upper bounds" mitigation from SURVEY.md §7 hard part 1).

Multi-column equi-keys are packed into one int64 by the planner (key
columns are bounded by table cardinalities, known from connector stats).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..batch import Batch, Column

_SENTINEL = jnp.iinfo(jnp.int64).max


def _combined_key(batch: Batch, key_indices: tuple) -> Tuple[jax.Array,
                                                             jax.Array]:
    """(key, key_valid) as int64. Multi-column keys pack 32 bits per
    trailing column (key columns are table keys bounded well below 2^31;
    the executor validates ranges host-side before taking this path)."""
    col = batch.columns[key_indices[0]]
    key = col.data.astype(jnp.int64)
    valid = col.valid
    for ki in key_indices[1:]:
        c = batch.columns[ki]
        key = key * (1 << 32) + c.data.astype(jnp.int64)
        valid = valid & c.valid
    return key, valid


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def join_unique_build(probe: Batch, build: Batch, probe_keys: tuple,
                      build_keys: tuple, kind: str):
    """Equi-join where the build side is unique on its key.

    kind: 'inner' | 'left' | 'semi' | 'anti'.
    Returns (out_batch, dup_count) where dup_count>0 means the uniqueness
    assumption failed and the caller must re-run on the fallback path.
    - inner/left: output = probe columns ++ build columns (gathered)
    - semi/anti: output = probe columns, live-mask filtered
    """
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)

    # dead or NULL-keyed build rows sort to +inf and never match
    bk_eff = jnp.where(build.live & bk_valid, bk, _SENTINEL)
    n_build = build.capacity
    sorted_keys, order = jax.lax.sort((bk_eff, jnp.arange(
        n_build, dtype=jnp.int32)), num_keys=1)

    dup = jnp.sum((sorted_keys[1:] == sorted_keys[:-1]) &
                  (sorted_keys[1:] != _SENTINEL))

    pos = jnp.searchsorted(sorted_keys, pk)
    pos_c = jnp.clip(pos, 0, n_build - 1)
    matched = (sorted_keys[pos_c] == pk) & pk_valid & (pk != _SENTINEL)
    src = order[pos_c]

    if kind == "semi":
        return probe.with_live(probe.live & matched), dup
    if kind == "anti":
        # EXISTS-complement: a NULL probe key matches nothing, so the row
        # survives NOT EXISTS. NOT IN's null-awareness is the planner's
        # job (IS NOT NULL pre-filter + executor build-null check).
        return probe.with_live(probe.live & ~matched), dup

    build_cols = []
    for col in build.columns:
        data = col.data[src]
        valid = col.valid[src] & matched
        build_cols.append(Column(data=data, valid=valid))
    if kind == "inner":
        live = probe.live & matched
    else:  # left
        live = probe.live
    return Batch(columns=probe.columns + tuple(build_cols), live=live), dup


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def join_expand(probe: Batch, build: Batch, probe_keys: tuple,
                build_keys: tuple, kind: str, out_capacity: int):
    """Equi-join with arbitrary build-side multiplicity (1:N fan-out),
    fully on device and scatter-free.

    Two-pass expansion (the TPU answer to LookupJoinOperator's variable
    JoinProbe fan-out, operator/join/unspilled/PageJoiner.java:138):
    1. per-probe-row match counts via sorted build + two searchsorteds;
    2. output row j maps back to its probe row by binary search on the
       cumulative count array, and to its build row by offset within the
       match run — both gathers.

    Returns (out_batch, total_rows); total_rows > out_capacity means the
    static bound overflowed and the caller must grow and retry (executor
    does, like the sort-agg capacity retry).
    kind: 'inner' | 'left'.
    """
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)
    n_build = build.capacity
    n_probe = probe.capacity

    bk_eff = jnp.where(build.live & bk_valid, bk, _SENTINEL)
    sorted_keys, order = jax.lax.sort(
        (bk_eff, jnp.arange(n_build, dtype=jnp.int32)), num_keys=1)

    lo = jnp.searchsorted(sorted_keys, pk, side="left")
    hi = jnp.searchsorted(sorted_keys, pk, side="right")
    pk_ok = probe.live & pk_valid & (pk != _SENTINEL)
    counts = jnp.where(pk_ok, hi - lo, 0)
    if kind == "left":
        out_counts = jnp.maximum(counts, probe.live.astype(counts.dtype))
    else:
        out_counts = counts
    cum = jnp.cumsum(out_counts)
    total = cum[n_probe - 1]

    j = jnp.arange(out_capacity, dtype=cum.dtype)
    probe_row = jnp.searchsorted(cum, j, side="right")
    probe_row_c = jnp.clip(probe_row, 0, n_probe - 1)
    before = jnp.where(probe_row_c > 0,
                       cum[jnp.clip(probe_row_c - 1, 0, n_probe - 1)], 0)
    within = j - before
    out_live = j < total
    matched = out_live & (within < counts[probe_row_c])
    build_row = order[jnp.clip(lo[probe_row_c] + within, 0, n_build - 1)]

    out_cols = []
    for col in probe.columns:
        out_cols.append(Column(data=col.data[probe_row_c],
                               valid=col.valid[probe_row_c] & out_live))
    for col in build.columns:
        out_cols.append(Column(data=col.data[build_row],
                               valid=col.valid[build_row] & matched))
    return Batch(columns=tuple(out_cols), live=out_live), total


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def join_mark(probe: Batch, build: Batch, probe_keys: tuple,
              build_keys: tuple, residual, out_capacity: int):
    """Mark join: per probe row, does ANY build row match the equi keys AND
    the residual predicate? Powers semi/anti joins with non-equi correlated
    conditions (TPC-H q21's l2.l_suppkey <> l1.l_suppkey), the role of
    Trino's JoinFilterFunction on semi joins
    (sql/gen/JoinFilterFunctionCompiler.java).

    Same two-pass expansion as join_expand; the residual is evaluated over
    the expanded pair batch (probe columns ++ build columns), then reduced
    back per probe row with a cumulative-count window — scatter-free.

    Returns (mark_bool_per_probe_row, total_pairs). total_pairs >
    out_capacity means the expansion overflowed; caller grows and retries.
    """
    from .project import filter_mask

    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)
    n_build = build.capacity
    n_probe = probe.capacity

    bk_eff = jnp.where(build.live & bk_valid, bk, _SENTINEL)
    sorted_keys, order = jax.lax.sort(
        (bk_eff, jnp.arange(n_build, dtype=jnp.int32)), num_keys=1)

    lo = jnp.searchsorted(sorted_keys, pk, side="left")
    hi = jnp.searchsorted(sorted_keys, pk, side="right")
    pk_ok = probe.live & pk_valid & (pk != _SENTINEL)
    counts = jnp.where(pk_ok, hi - lo, 0)
    cum = jnp.cumsum(counts)
    total = cum[n_probe - 1]

    j = jnp.arange(out_capacity, dtype=cum.dtype)
    probe_row = jnp.searchsorted(cum, j, side="right")
    probe_row_c = jnp.clip(probe_row, 0, n_probe - 1)
    before = jnp.where(probe_row_c > 0,
                       cum[jnp.clip(probe_row_c - 1, 0, n_probe - 1)], 0)
    within = j - before
    pair_live = (j < total) & (within < counts[probe_row_c])
    build_row = order[jnp.clip(lo[probe_row_c] + within, 0, n_build - 1)]

    pair_cols = []
    for col in probe.columns:
        pair_cols.append(Column(data=col.data[probe_row_c],
                                valid=col.valid[probe_row_c] & pair_live))
    for col in build.columns:
        pair_cols.append(Column(data=col.data[build_row],
                                valid=col.valid[build_row] & pair_live))
    pairs = Batch(columns=tuple(pair_cols), live=pair_live)
    ok = filter_mask(residual, pairs) & pair_live if residual is not None \
        else pair_live

    # per-probe-row "any ok": windowed sum over the cumulative ok counts
    cs = jnp.cumsum(ok.astype(jnp.int64))
    start = jnp.clip(jnp.minimum(cum - counts, out_capacity - 1), 0, None)
    end = jnp.clip(cum - 1, 0, out_capacity - 1)
    upto_end = cs[end]
    before_start = jnp.where(start > 0, cs[jnp.clip(start - 1, 0,
                                                    out_capacity - 1)], 0)
    any_ok = (counts > 0) & ((upto_end - before_start) > 0)
    return any_ok, total
