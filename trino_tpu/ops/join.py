"""Join kernels.

Reference: Trino's lookup join — HashBuilderOperator fills a PagesIndex and
builds a JoinHash; LookupJoinOperator probes it per page
(operator/join/unspilled/HashBuilderOperator.java:48,
unspilled/LookupJoinOperator.java:41, PageJoiner.java:138).

TPUs lack efficient pointer-chasing, so the build structure is a *sorted key
array* and the probe is a vectorized binary search (`searchsorted`, which
XLA lowers to a fully parallel per-lane search) — exact, static-shape, no
hash collisions (SURVEY.md §7 "GroupBy/Join on TPU").

Unique-build joins (key is a primary key: every TPC-H dimension join) have
fan-out <= 1, so output capacity == probe capacity and everything stays on
device. Duplicate-build joins report a duplicate count; the executor falls
back to a host expansion join (the "conservative upper bounds with overflow
spill to a host path" mitigation from SURVEY.md §7 hard part 1) until the
device multi-match expansion lands.

Multi-column equi-keys are packed into one int64 by the planner (key
columns are bounded by table cardinalities, known from connector stats).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import Batch, Column

_SENTINEL = jnp.iinfo(jnp.int64).max


def _combined_key(batch: Batch, key_indices: tuple) -> Tuple[jax.Array,
                                                             jax.Array]:
    """(key, key_valid) as int64. Multi-column keys pack 32 bits per
    trailing column (key columns are table keys bounded well below 2^31;
    the executor validates ranges host-side before taking this path)."""
    col = batch.columns[key_indices[0]]
    key = col.data.astype(jnp.int64)
    valid = col.valid
    for ki in key_indices[1:]:
        c = batch.columns[ki]
        key = key * (1 << 32) + c.data.astype(jnp.int64)
        valid = valid & c.valid
    return key, valid


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def join_unique_build(probe: Batch, build: Batch, probe_keys: tuple,
                      build_keys: tuple, kind: str):
    """Equi-join where the build side is unique on its key.

    kind: 'inner' | 'left' | 'semi' | 'anti'.
    Returns (out_batch, dup_count) where dup_count>0 means the uniqueness
    assumption failed and the caller must re-run on the fallback path.
    - inner/left: output = probe columns ++ build columns (gathered)
    - semi/anti: output = probe columns, live-mask filtered
    """
    pk, pk_valid = _combined_key(probe, probe_keys)
    bk, bk_valid = _combined_key(build, build_keys)

    # dead or NULL-keyed build rows sort to +inf and never match
    bk_eff = jnp.where(build.live & bk_valid, bk, _SENTINEL)
    n_build = build.capacity
    sorted_keys, order = jax.lax.sort((bk_eff, jnp.arange(
        n_build, dtype=jnp.int32)), num_keys=1)

    dup = jnp.sum((sorted_keys[1:] == sorted_keys[:-1]) &
                  (sorted_keys[1:] != _SENTINEL))

    pos = jnp.searchsorted(sorted_keys, pk)
    pos_c = jnp.clip(pos, 0, n_build - 1)
    matched = (sorted_keys[pos_c] == pk) & pk_valid & (pk != _SENTINEL)
    src = order[pos_c]

    if kind == "semi":
        return probe.with_live(probe.live & matched), dup
    if kind == "anti":
        # NULL probe keys never match and never fail to match: SQL NOT IN
        # semantics are handled by the planner (this is the semi-join
        # complement used for correlated-exists rewrites)
        return probe.with_live(probe.live & ~matched & pk_valid), dup

    build_cols = []
    for col in build.columns:
        data = col.data[src]
        valid = col.valid[src] & matched
        build_cols.append(Column(data=data, valid=valid))
    if kind == "inner":
        live = probe.live & matched
    else:  # left
        live = probe.live
    return Batch(columns=probe.columns + tuple(build_cols), live=live), dup


def host_expansion_join(probe_arrays, probe_valids, probe_live,
                        build_arrays, build_valids, build_live,
                        probe_key_idx: int, build_key_idx: int,
                        kind: str):
    """Host numpy fallback for duplicate build keys (1:N fan-out).

    The spill-to-host path: correct for any multiplicity; used until the
    device two-pass expansion kernel lands. Returns (arrays, valids) for
    probe ++ build columns, live rows only.
    """
    p_live = probe_live
    b_live = build_live
    pk = probe_arrays[probe_key_idx]
    pk_ok = p_live & probe_valids[probe_key_idx]
    bk = build_arrays[build_key_idx]
    bk_ok = b_live & build_valids[build_key_idx]

    b_idx = np.nonzero(bk_ok)[0]
    order = b_idx[np.argsort(bk[b_idx], kind="stable")]
    bk_sorted = bk[order]
    lo = np.searchsorted(bk_sorted, pk, side="left")
    hi = np.searchsorted(bk_sorted, pk, side="right")
    counts = np.where(pk_ok, hi - lo, 0)

    if kind == "semi":
        keep = p_live & (counts > 0)
        return ([a[keep] for a in probe_arrays],
                [v[keep] for v in probe_valids])
    if kind == "anti":
        keep = p_live & (counts == 0) & probe_valids[probe_key_idx]
        return ([a[keep] for a in probe_arrays],
                [v[keep] for v in probe_valids])

    if kind == "left":
        out_counts = np.maximum(counts, p_live.astype(np.int64))
    else:
        out_counts = counts
    probe_rows = np.repeat(np.arange(len(pk)), out_counts)
    offsets = np.concatenate([[0], np.cumsum(out_counts)[:-1]])
    within = np.arange(len(probe_rows)) - offsets[probe_rows]
    matched = within < counts[probe_rows]
    build_rows = np.where(
        matched, order[np.clip(lo[probe_rows] + within, 0,
                               max(len(order) - 1, 0))], 0)
    arrays = [a[probe_rows] for a in probe_arrays]
    valids = [v[probe_rows] for v in probe_valids]
    for a, v in zip(build_arrays, build_valids):
        arrays.append(np.where(matched, a[build_rows], 0))
        valids.append(np.where(matched, v[build_rows], False))
    return arrays, valids
