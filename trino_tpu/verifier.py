"""Query verifier: control-vs-test result diffing.

Reference: service/trino-verifier (Verifier.java:57, Validator.java) runs
every query against a control and a test cluster and reports row-level
differences — the correctness harness behind "identical results" claims.

Here: control = sqlite3 over the same generated data (the oracle), test =
this engine. Usable as a library (`Verifier.run_suite`) or a CLI:

    python -m trino_tpu.verifier --suite tpch
    python -m trino_tpu.verifier --suite tpcds
    python -m trino_tpu.verifier -e "SELECT count(*) FROM nation"
"""

from __future__ import annotations

import argparse
import sqlite3
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .exec.session import Session


@dataclass
class VerifyResult:
    name: str
    status: str                  # MATCH | MISMATCH | CONTROL_ERROR |
                                 # TEST_ERROR | SKIPPED
    detail: str = ""
    control_rows: int = 0
    test_rows: int = 0
    control_ms: float = 0.0
    test_ms: float = 0.0


class Verifier:
    def __init__(self, session: Session, tables: List[str],
                 rel_tol: float = 1e-9, abs_tol: float = 0.01):
        self.session = session
        self.rel_tol = rel_tol
        self.abs_tol = abs_tol
        self._load_control(tables)

    def _load_control(self, tables: List[str]) -> None:
        from .connectors.tpch.datagen import TableData  # noqa: F401
        conn = self.session.catalog.connector(self.session.default_cat)
        datasets = [conn.get_table(self.session.default_schema, t)
                    for t in tables]
        # reuse the oracle loader living beside the tests when available;
        # otherwise load directly
        self.control = _load_sqlite(datasets)

    # per-query wall cap: a wedged accelerator tunnel HANGS inside a
    # native call (signals cannot interrupt it), so the watchdog is a
    # thread that records the timeout and hard-exits the process — with
    # --resume, the next invocation picks up after the recorded queries
    # (Verifier.java's per-query timeout, adapted to the tunnel reality)
    query_timeout_s: Optional[float] = None
    on_timeout = None          # callable(name) -> None, set by the CLI

    def verify(self, name: str, sql: str,
               control_sql: Optional[str] = None) -> VerifyResult:
        t0 = time.monotonic()
        watchdog = None
        try:
            if self.query_timeout_s:
                import os
                import threading

                def _expired():
                    if self.on_timeout is not None:
                        try:
                            self.on_timeout(name)
                        except Exception:    # noqa: BLE001
                            pass
                    print(f"TIMEOUT {name}: exceeded "
                          f"{self.query_timeout_s}s (wedged tunnel?); "
                          f"exiting — rerun with --resume", flush=True)
                    os._exit(3)
                watchdog = threading.Timer(self.query_timeout_s, _expired)
                watchdog.daemon = True
                watchdog.start()
            test_rows = self.session.execute(sql).rows
        except Exception as e:            # noqa: BLE001
            return VerifyResult(name, "TEST_ERROR", f"{e}")
        finally:
            if watchdog is not None:
                watchdog.cancel()
        test_ms = (time.monotonic() - t0) * 1000
        t0 = time.monotonic()
        try:
            cur = self.control.execute(
                _translate(control_sql or sql))
            control_rows = cur.fetchall()
        except Exception as e:            # noqa: BLE001
            return VerifyResult(name, "CONTROL_ERROR", f"{e}")
        control_ms = (time.monotonic() - t0) * 1000
        diff = self._diff(test_rows, control_rows)
        return VerifyResult(
            name, "MATCH" if diff is None else "MISMATCH", diff or "",
            len(control_rows), len(test_rows), control_ms, test_ms)

    def _diff(self, got, want) -> Optional[str]:
        if len(got) != len(want):
            return f"row count: test={len(got)} control={len(want)}"
        for i, (g, w) in enumerate(zip(got, want)):
            if len(g) != len(w):
                return f"row {i} arity: {len(g)} vs {len(w)}"
            for j, (a, b) in enumerate(zip(g, w)):
                if a is None or b is None:
                    if a is not b and not (a is None and b is None):
                        return f"row {i} col {j}: {a!r} != {b!r}"
                    continue
                if isinstance(a, float) or isinstance(b, float) or \
                        type(a).__name__ == "Decimal":
                    af, bf = float(a), float(b)
                    tol = max(self.abs_tol,
                              self.rel_tol * max(abs(af), abs(bf)))
                    if abs(af - bf) > tol:
                        return f"row {i} col {j}: {af} != {bf}"
                elif str(a) != str(b) and a != b:
                    return f"row {i} col {j}: {a!r} != {b!r}"
        return None

    def run_suite(self, queries: Dict[object, str],
                  on_result=None) -> List[VerifyResult]:
        out = []
        for k, sql in sorted(queries.items(), key=lambda kv: str(kv[0])):
            r = self.verify(str(k), sql)
            if on_result is not None:
                on_result(r)
            out.append(r)
        return out


# -- sqlite loading / dialect translation (shared with tests/oracle.py) ----

class _SqliteVar:
    """Welford variance aggregate for the sqlite control (it ships none)."""
    samp = True
    sqrt = False

    def __init__(self):
        self.n, self.mean, self.m2 = 0, 0.0, 0.0

    def step(self, x):
        if x is None:
            return
        self.n += 1
        d = x - self.mean
        self.mean += d / self.n
        self.m2 += d * (x - self.mean)

    def finalize(self):
        denom = (self.n - 1) if self.samp else self.n
        if denom <= 0:
            return None
        v = self.m2 / denom
        return v ** 0.5 if self.sqrt else v


def _load_sqlite(datasets) -> sqlite3.Connection:
    import numpy as np

    from .types import TypeKind
    conn = sqlite3.connect(":memory:")
    for name, samp, sq in [("var_samp", True, False),
                           ("variance", True, False),
                           ("var_pop", False, False),
                           ("stddev", True, True),
                           ("stddev_samp", True, True),
                           ("stddev_pop", False, True)]:
        cls = type(name, (_SqliteVar,), {"samp": samp, "sqrt": sq})
        conn.create_aggregate(name, 1, cls)
    for t in datasets:
        cols = []
        for f in t.schema:
            k = f.dtype.kind
            if k in (TypeKind.VARCHAR, TypeKind.DATE):
                cols.append(f"{f.name} TEXT")
            elif k in (TypeKind.DOUBLE, TypeKind.DECIMAL):
                cols.append(f"{f.name} REAL")
            else:
                cols.append(f"{f.name} INTEGER")
        conn.execute(f"CREATE TABLE {t.name} ({', '.join(cols)})")
        host_cols = []
        for f, arr in zip(t.schema, t.columns):
            k = f.dtype.kind
            if k is TypeKind.VARCHAR:
                pool = np.array(f.dictionary, dtype=object)
                host_cols.append(pool[np.asarray(arr)])
            elif k is TypeKind.DATE:
                base = np.datetime64("1970-01-01")
                host_cols.append((base + np.asarray(arr)).astype(str))
            elif k is TypeKind.DECIMAL:
                host_cols.append(np.asarray(arr) / (10 ** f.dtype.scale))
            else:
                host_cols.append(np.asarray(arr))
        if t.valids is not None:
            for j, v in enumerate(t.valids):
                if v is None:
                    continue
                col = np.asarray(host_cols[j], dtype=object)
                col[~np.asarray(v)] = None
                host_cols[j] = col
        rows = list(zip(*[c.tolist() for c in host_cols]))
        ph = ", ".join("?" * len(t.schema))
        conn.executemany(f"INSERT INTO {t.name} VALUES ({ph})", rows)
        # surrogate-key indexes keep sqlite's nested-loop plans tractable
        # on star-join benchmark queries
        for f in t.schema:
            if f.name.endswith("_sk") or f.name.endswith("key"):
                conn.execute(f"CREATE INDEX IF NOT EXISTS "
                             f"idx_{t.name}_{f.name} ON {t.name}({f.name})")
    conn.execute("ANALYZE")
    conn.commit()
    return conn


def _translate(sql: str) -> str:
    """Engine dialect -> sqlite (DATE literals, interval folding,
    EXTRACT)."""
    import datetime
    import re

    def fold_interval(m):
        d = datetime.date.fromisoformat(m.group(1))
        n = int(m.group(3))
        unit = m.group(4).lower().rstrip("s")
        sign = -1 if m.group(2) == "-" else 1
        if unit == "day":
            d2 = d + datetime.timedelta(days=sign * n)
        else:
            months = sign * n * (12 if unit == "year" else 1)
            y, m0 = divmod(d.year * 12 + d.month - 1 + months, 12)
            day = min(d.day, 28)
            d2 = datetime.date(y, m0 + 1, day)
        return f"'{d2.isoformat()}'"

    sql = re.sub(
        r"DATE\s*'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*INTERVAL\s*"
        r"'(\d+)'\s*(\w+)", fold_interval, sql, flags=re.I)
    sql = re.sub(r"DATE\s*'(\d{4}-\d{2}-\d{2})'", r"'\1'", sql,
                 flags=re.I)
    sql = re.sub(r"EXTRACT\s*\(\s*YEAR\s+FROM\s+([^)]+)\)",
                 r"CAST(strftime('%Y', \1) AS INTEGER)", sql, flags=re.I)
    sql = re.sub(r"\bsubstring\s*\(", "substr(", sql, flags=re.I)
    return sql


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu-verifier")
    ap.add_argument("--suite", choices=["tpch", "tpcds"])
    ap.add_argument("--execute", "-e", help="verify one statement")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--platform", choices=["cpu", "tpu"],
                    help="force a JAX platform (env vars are overridden "
                         "by accelerator tunnels; the config API wins)")
    ap.add_argument("--timeout-s", type=float, default=0,
                    help="per-query wall cap (0 = none): a wedged tunnel "
                         "hangs, this turns it into TEST_TIMEOUT")
    ap.add_argument("--resume", metavar="FILE",
                    help="append results to FILE (jsonl) and skip "
                         "queries already recorded there — a killed "
                         "sweep resumes instead of restarting")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms",
                          "cpu" if args.platform == "cpu" else None)

    if args.suite == "tpcds":
        from .connectors.tpcds.connector import TABLE_NAMES
        session = Session(default_cat="tpcds", default_schema=args.schema)
        tables = list(TABLE_NAMES)
    else:
        from .connectors.tpch.connector import TABLE_NAMES
        session = Session(default_cat="tpch", default_schema=args.schema)
        tables = list(TABLE_NAMES)
    verifier = Verifier(session, tables)

    if args.execute:
        r = verifier.verify("adhoc", args.execute)
        print(f"{r.status}: {r.detail or f'{r.test_rows} rows'}")
        return 0 if r.status == "MATCH" else 1

    queries: Dict[object, str] = {}
    if args.suite == "tpch":
        sys.path.insert(0, "tests")
        try:
            from tpch_full import QUERIES as queries  # type: ignore
        except ImportError:
            pass
    elif args.suite == "tpcds":
        sys.path.insert(0, "tests")
        try:
            from tpcds_queries import QUERIES as queries  # type: ignore
        except ImportError:
            pass
    if args.timeout_s:
        verifier.query_timeout_s = args.timeout_s
        if args.resume:
            def _record_timeout(name):
                import json
                with open(args.resume, "a") as f:
                    f.write(json.dumps(
                        {"name": name, "status": "TEST_TIMEOUT",
                         "test_ms": args.timeout_s * 1000,
                         "detail": "watchdog hard-exit"}) + "\n")
            verifier.on_timeout = _record_timeout

    done = {}
    if args.resume:
        import json
        import os.path
        timeouts = {}
        if os.path.exists(args.resume):
            with open(args.resume) as f:
                for line in f:
                    rec = json.loads(line)
                    done[rec["name"]] = rec["status"]
                    if rec["status"] == "TEST_TIMEOUT":
                        timeouts[rec["name"]] = \
                            timeouts.get(rec["name"], 0) + 1
        # retry non-MATCH (a fresh attempt resumes cached compiles and
        # gets further), but give up on a query that timed out 3 times —
        # those count as FAILURES in the summary/exit code, never as
        # verified.
        gave_up = sorted(k for k in list(queries)
                         if str(k) in done and done[str(k)] != "MATCH"
                         and timeouts.get(str(k), 0) >= 3)
        queries = {k: v for k, v in queries.items()
                   if str(k) not in done or
                   (done[str(k)] != "MATCH" and
                    timeouts.get(str(k), 0) < 3)}
        if done:
            print(f"resuming: {len(done)} recorded, "
                  f"{len(queries)} to run, "
                  f"{len(gave_up)} given up (count as FAIL)", flush=True)
    else:
        gave_up = []

    def show(r):
        mark = "OK " if r.status == "MATCH" else "FAIL"
        print(f"{mark} {r.name:>6}  {r.status:14} test={r.test_ms:8.1f}ms "
              f"control={r.control_ms:8.1f}ms rows={r.test_rows}"
              + (f"  {r.detail}" if r.detail else ""), flush=True)
        if args.resume:
            import json
            with open(args.resume, "a") as f:
                f.write(json.dumps({"name": r.name, "status": r.status,
                                    "test_ms": r.test_ms,
                                    "detail": r.detail[:200]}) + "\n")

    results = verifier.run_suite(queries, on_result=show)
    fails = sum(r.status != "MATCH" for r in results) + len(gave_up)
    prior = sum(1 for s in done.values() if s == "MATCH")
    total = len(results) + prior + len(gave_up)
    print(f"{total - fails}/{total} queries verified identical"
          + (f" ({len(gave_up)} permanently timed out: "
             f"{', '.join(str(g) for g in gave_up)})" if gave_up else ""))
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
