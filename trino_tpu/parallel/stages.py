"""Distributed stage programs: whole plan fragments as SPMD programs.

Reference: a Trino PlanFragment runs as N tasks exchanging pages
(PlanFragmenter.java:126, SURVEY.md §3.3); here a fragment is ONE jitted
`shard_map` program over the mesh — scan shards play the role of tasks,
collectives play the exchanges. XLA sees the whole stage (scan -> filter ->
project -> repartition -> join -> partial agg -> merge) and fuses across
operator boundaries, which is the reference's PageProcessor codegen +
exchange serde collapsed into one compile.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import ir
from ..batch import Batch
from ..ops.aggregate import direct_group_aggregate
from ..ops.join import join_unique_build
from ..ops.project import apply_filter, project
from .exchange import (apply_filter_bounds, join_filter_bounds,
                       merge_partial_states, repartition_by_key)
from .mesh import AXIS, shard_map


def sharded_agg_step(mesh, filter_expr, pre_exprs, key_indices: tuple,
                     domains: tuple, aggs: tuple):
    """Distributed GROUP BY (q1 shape): per-shard filter/project/partial
    aggregate, then collective merge. The dense direct-strategy table makes
    the merge a pure psum/pmin/pmax — no key exchange at all (every shard
    shares the same group-id space), which is strictly cheaper than the
    reference's hash repartition between PARTIAL and FINAL."""
    agg_funcs = tuple(a.func for a in aggs)
    n_keys = len(key_indices)

    def body(local: Batch) -> Batch:
        if filter_expr is not None:
            local = apply_filter(local, filter_expr)
        if pre_exprs is not None:
            local = project(local, pre_exprs)
        partial = direct_group_aggregate(local, key_indices, domains, aggs)
        return merge_partial_states(partial, agg_funcs, n_keys)

    mapped = shard_map(body, mesh=mesh, in_specs=(P(AXIS),),
                       out_specs=P())
    return jax.jit(mapped)


def sharded_join_agg_step(mesh, n_shards: int,
                          probe_filter, probe_key: int,
                          build_filter, build_key: int,
                          post_exprs, agg_keys: tuple, domains: tuple,
                          aggs: tuple):
    """Distributed equi-join + aggregation (q3/q5 shape):

    probe shards --filter--> all_to_all(hash(key))    [PartitionedOutput]
    build shards --filter--> all_to_all(hash(key))    [+ExchangeOperator]
    -> co-partitioned local joins (build stays unique per partition,
       since hash partitioning sends all rows of one key to one shard)
    -> post-project -> partial dense aggregate -> psum merge [FINAL agg]
    """
    agg_funcs = tuple(a.func for a in aggs)
    n_keys = len(agg_keys)

    def body(probe: Batch, build: Batch):
        if probe_filter is not None:
            probe = apply_filter(probe, probe_filter)
        if build_filter is not None:
            build = apply_filter(build, build_filter)
        probe = repartition_by_key(probe, probe_key, n_shards)
        build = repartition_by_key(build, build_key, n_shards)
        joined, dup = join_unique_build(probe, build, (probe_key,),
                                        (build_key,), "inner")
        if post_exprs is not None:
            joined = project(joined, post_exprs)
        partial = direct_group_aggregate(joined, agg_keys, domains, aggs)
        # surface build-key duplicates: hash partitioning co-locates all
        # rows of a key, so a duplicate would silently drop join rows —
        # the caller must check total_dups == 0 and fall back to the
        # general expansion path (MeshExecutor) otherwise
        total_dups = jax.lax.psum(dup, AXIS)
        return merge_partial_states(partial, agg_funcs, n_keys), total_dups

    mapped = shard_map(body, mesh=mesh,
                       in_specs=(P(AXIS), P(AXIS)),
                       out_specs=(P(), P()))
    return jax.jit(mapped)


def broadcast_join_step(mesh, probe_filter, probe_keys: tuple,
                        build_keys: tuple, post_exprs):
    """Broadcast-build join (DetermineJoinDistributionType's REPLICATED
    choice): build side replicated, probe stays sharded, no exchange on the
    probe — output remains row-sharded for downstream stages."""

    def body(probe: Batch, build: Batch) -> Batch:
        if probe_filter is not None:
            probe = apply_filter(probe, probe_filter)
        joined, _dup = join_unique_build(probe, build, probe_keys,
                                         build_keys, "inner")
        if post_exprs is not None:
            joined = project(joined, post_exprs)
        return joined

    mapped = shard_map(body, mesh=mesh, in_specs=(P(AXIS), P()),
                       out_specs=P(AXIS))
    return jax.jit(mapped)


def partitioned_hash_join_step(mesh, n_shards: int, probe_keys: tuple,
                               build_keys: tuple, kind: str,
                               table_slots: int, hash_mode: str,
                               gather_mode: str = "off",
                               dynamic_filter: bool = True):
    """Mesh-partitioned hybrid hash join (PARTITIONED distribution):

    build shards --bounds--> ONE all_gather          [DynamicFilterSource]
    probe shards --prune---> all_to_all(hash(key))   [PartitionedOutput]
    build shards ----------> all_to_all(hash(key))   [+ExchangeOperator]
    -> per-shard VMEM hash build + probe (ops/pallas_hash.py): each chip
       owns 1/N of the key space, so the per-chip table shrinks N x and
       probe gathers stay local to ICI.

    Dynamic filtering is BATCHED into this same jitted program: the
    build-key bounds collective and the probe prune live in one XLA
    module with the join, so the per-probe cross-module rendezvous that
    deadlocked the old mesh path (TPC-DS q77) cannot occur by
    construction. Returns (joined row-sharded, total_dup, total_escape,
    total_pruned); the caller checks dup (fall back to the expansion
    join) and escape (skewed partition overflowed its table — degrade
    to the host equi-join like the single-chip hybrid join)."""
    from ..ops import pallas_hash as ph

    def body(probe: Batch, build: Batch):
        kmins, kmaxs = join_filter_bounds(build, build_keys)
        if dynamic_filter:
            probe, pruned = apply_filter_bounds(probe, probe_keys,
                                                kmins, kmaxs)
        else:
            pruned = jnp.zeros((), jnp.int64)
        probe = repartition_by_key(probe, probe_keys[0], n_shards)
        build = repartition_by_key(build, build_keys[0], n_shards)
        joined, dup, esc = ph.shard_join(
            probe, build, probe_keys, build_keys, kind, table_slots,
            hash_mode, gather_mode)
        return (joined, jax.lax.psum(dup, AXIS),
                jax.lax.psum(esc, AXIS), jax.lax.psum(pruned, AXIS))

    mapped = shard_map(body, mesh=mesh,
                       in_specs=(P(AXIS), P(AXIS)),
                       out_specs=(P(AXIS), P(), P(), P()))
    return jax.jit(mapped)
