"""Distributed stage programs: whole plan fragments as SPMD programs.

Reference: a Trino PlanFragment runs as N tasks exchanging pages
(PlanFragmenter.java:126, SURVEY.md §3.3); here a fragment is ONE jitted
`shard_map` program over the mesh — scan shards play the role of tasks,
collectives play the exchanges. XLA sees the whole stage (scan -> filter ->
project -> repartition -> join -> partial agg -> merge) and fuses across
operator boundaries, which is the reference's PageProcessor codegen +
exchange serde collapsed into one compile.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import ir
from ..batch import Batch
from ..ops.aggregate import direct_group_aggregate
from ..ops.join import join_unique_build
from ..ops.project import apply_filter, project
from .exchange import merge_partial_states, repartition_by_key
from .mesh import AXIS


def sharded_agg_step(mesh, filter_expr, pre_exprs, key_indices: tuple,
                     domains: tuple, aggs: tuple):
    """Distributed GROUP BY (q1 shape): per-shard filter/project/partial
    aggregate, then collective merge. The dense direct-strategy table makes
    the merge a pure psum/pmin/pmax — no key exchange at all (every shard
    shares the same group-id space), which is strictly cheaper than the
    reference's hash repartition between PARTIAL and FINAL."""
    agg_funcs = tuple(a.func for a in aggs)
    n_keys = len(key_indices)

    def body(local: Batch) -> Batch:
        if filter_expr is not None:
            local = apply_filter(local, filter_expr)
        if pre_exprs is not None:
            local = project(local, pre_exprs)
        partial = direct_group_aggregate(local, key_indices, domains, aggs)
        return merge_partial_states(partial, agg_funcs, n_keys)

    mapped = jax.shard_map(body, mesh=mesh, in_specs=(P(AXIS),),
                           out_specs=P(), check_vma=False)
    return jax.jit(mapped)


def sharded_join_agg_step(mesh, n_shards: int,
                          probe_filter, probe_key: int,
                          build_filter, build_key: int,
                          post_exprs, agg_keys: tuple, domains: tuple,
                          aggs: tuple):
    """Distributed equi-join + aggregation (q3/q5 shape):

    probe shards --filter--> all_to_all(hash(key))    [PartitionedOutput]
    build shards --filter--> all_to_all(hash(key))    [+ExchangeOperator]
    -> co-partitioned local joins (build stays unique per partition,
       since hash partitioning sends all rows of one key to one shard)
    -> post-project -> partial dense aggregate -> psum merge [FINAL agg]
    """
    agg_funcs = tuple(a.func for a in aggs)
    n_keys = len(agg_keys)

    def body(probe: Batch, build: Batch):
        if probe_filter is not None:
            probe = apply_filter(probe, probe_filter)
        if build_filter is not None:
            build = apply_filter(build, build_filter)
        probe = repartition_by_key(probe, probe_key, n_shards)
        build = repartition_by_key(build, build_key, n_shards)
        joined, dup = join_unique_build(probe, build, (probe_key,),
                                        (build_key,), "inner")
        if post_exprs is not None:
            joined = project(joined, post_exprs)
        partial = direct_group_aggregate(joined, agg_keys, domains, aggs)
        # surface build-key duplicates: hash partitioning co-locates all
        # rows of a key, so a duplicate would silently drop join rows —
        # the caller must check total_dups == 0 and fall back to the
        # general expansion path (MeshExecutor) otherwise
        total_dups = jax.lax.psum(dup, AXIS)
        return merge_partial_states(partial, agg_funcs, n_keys), total_dups

    mapped = jax.shard_map(body, mesh=mesh,
                           in_specs=(P(AXIS), P(AXIS)),
                           out_specs=(P(), P()),
                           check_vma=False)
    return jax.jit(mapped)


def broadcast_join_step(mesh, probe_filter, probe_keys: tuple,
                        build_keys: tuple, post_exprs):
    """Broadcast-build join (DetermineJoinDistributionType's REPLICATED
    choice): build side replicated, probe stays sharded, no exchange on the
    probe — output remains row-sharded for downstream stages."""

    def body(probe: Batch, build: Batch) -> Batch:
        if probe_filter is not None:
            probe = apply_filter(probe, probe_filter)
        joined, _dup = join_unique_build(probe, build, probe_keys,
                                         build_keys, "inner")
        if post_exprs is not None:
            joined = project(joined, post_exprs)
        return joined

    mapped = jax.shard_map(body, mesh=mesh, in_specs=(P(AXIS), P()),
                           out_specs=P(AXIS), check_vma=False)
    return jax.jit(mapped)
