"""Distributed plan executor: any logical plan over the device mesh.

Reference: the coordinator's planDistribution + worker task execution
(SqlQueryExecution.java:517, SURVEY.md §3.3) — a fragmented plan runs as
tasks on every worker, exchanging pages. TPU-native redesign (the
"How to Scale Your Model" recipe): keep the SINGLE global array program the
local executor already runs, place scan batches row-sharded over the mesh
(`NamedSharding(mesh, P('workers'))`), and let XLA's SPMD partitioner
insert the collectives a Trino cluster does by hand:

- masked group reductions    -> cross-shard psum      (= PARTIAL->FINAL agg)
- lax.sort for sort-groupby  -> distributed sort      (= hash repartition)
- join gathers               -> all_gather/all_to_all (= broadcast/
                                                         partitioned join)

The logical plan needs NO distributed rewrite: sharding is layout, not
semantics. Hand-tuned shard_map stage programs (parallel/stages.py) remain
the fast path for hot shapes; this executor is the general one — every SQL
feature the local executor supports runs distributed unchanged.

Join distribution (DetermineJoinDistributionType's choice, on the mesh):
the planner stamps JoinNode.distribution from build-size stats; BROADCAST
joins run the replicated default path below (XLA reads the build from
every shard), PARTITIONED joins hash-repartition both sides over the mesh
and run the VMEM hash kernel per shard
(parallel/stages.partitioned_hash_join_step) — each chip owns 1/N of the
key space. Skewed or duplicate-key partitions degrade exactly like the
single-chip hybrid join (host equi-join / expansion fallback).

Scheduling note: one process drives the whole mesh (single-controller JAX),
so the coordinator/worker HTTP runtime (server/) carries control-plane
semantics (states, liveness, retries) while data-plane parallelism lives
in XLA collectives over ICI. That division is the core architectural
difference from the reference's page-shuttling workers.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch, bucket_capacity
from ..catalog import Catalog
from ..exec.executor import Executor, compact_batch
from ..exec.profiler import recorded_jit
from ..planner import logical as L
from .mesh import AXIS, make_mesh, pad_to_multiple


@recorded_jit(static_argnums=(2, 3))
def _batched_dynamic_filter(probe: Batch, build: Batch,
                            probe_keys: tuple, build_keys: tuple):
    """ALL of one join's dynamic-filter bounds, mask, and pruned count
    in ONE jitted program. Over sharded operands GSPMD lowers the
    reductions into a single XLA module, so the mesh pays exactly one
    collective rendezvous per join — the structural fix for the old
    eager path, which dispatched one tiny cross-module all-reduce per
    bound and intermittently deadlocked the virtual-device runtime
    (rendezvous.cc "only 7 of 8 arrived", TPC-DS q77). Semantics match
    Executor.apply_dynamic_filter bit for bit."""
    keep = probe.live
    for pk_i, bk_i in zip(probe_keys, build_keys):
        bk = build.columns[bk_i]
        m = build.live & bk.valid
        info = jnp.iinfo(bk.data.dtype)
        kmin = jnp.min(jnp.where(m, bk.data, info.max))
        kmax = jnp.max(jnp.where(m, bk.data, info.min))
        pk = probe.columns[pk_i]
        keep = keep & pk.valid & (pk.data >= kmin) & (pk.data <= kmax)
    pruned = jnp.sum(probe.live, dtype=jnp.int64) - \
        jnp.sum(keep, dtype=jnp.int64)
    return keep, pruned


class MeshExecutor(Executor):
    """Executor whose scans land row-sharded on the mesh. Every operator
    kernel (already jitted) then runs as an SPMD program; XLA propagates
    shardings through the plan and inserts ICI collectives where global
    semantics require them."""

    # repartitioning doubles a side n_shards x during the exchange
    # (parallel/exchange.py's static bucket layout); above this estimate
    # the partitioned path would trade the gather win for an HBM cliff,
    # so the gate degrades to broadcast
    MESH_EXCHANGE_BUDGET_BYTES = 8 << 30

    def __init__(self, catalog: Catalog, mesh: Optional[Mesh] = None):
        super().__init__(catalog)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        # rows shard over every mesh axis (a 2-D hosts x chips mesh keeps
        # the inner collectives on ICI — see mesh.make_mesh_2d)
        self._row_sharding = NamedSharding(
            self.mesh, P(tuple(self.mesh.axis_names)))
        # Dynamic filtering used to be hard-pinned OFF here (a set-proof
        # property): its eager per-probe min/max over SHARDED build
        # columns dispatched a tiny cross-module all-reduce per bound,
        # and those independent rendezvous intermittently deadlocked the
        # virtual-CPU-device runtime (rendezvous.cc "only 7 of 8
        # arrived", deterministic on TPC-DS q77). The batched design
        # (_batched_dynamic_filter + join_filter_bounds inside
        # partitioned_hash_join_step) folds every filter collective into
        # the operator's own program, so that deadlock class cannot
        # occur; this flag remains as the session escape hatch
        # (mesh_dynamic_filtering=off).
        self.mesh_dynamic_filtering = True
        # compiled partitioned-join stage programs, keyed by static shape
        self._partitioned_steps: dict = {}

    def _decision_salt(self) -> tuple:
        # mesh knobs change decision values for the same plan structure
        # (the pruned-row count flips with the filter hatch; dup/escape
        # totals depend on the shard fanout)
        return super()._decision_salt() + (self.n_shards,
                                           self.mesh_dynamic_filtering)

    def _shard_batch(self, batch: Batch) -> Batch:
        """Row-shard a batch over the mesh (no-op for batches already
        laid out this way), padding odd capacities with dead rows."""
        batch = pad_to_multiple(batch, self.n_shards)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._row_sharding), batch)

    def run_scan(self, node: L.ScanNode) -> Batch:
        batch = super().run_scan(node)
        if batch.capacity % self.n_shards != 0:
            # odd capacity (mesh size does not divide the 1024-row
            # buckets): pad with dead rows to the next shard multiple
            # instead of silently staying single-device — the live mask
            # keeps padding invisible to every kernel
            batch = pad_to_multiple(batch, self.n_shards * 8)
        key = self._scan_key(node)
        sharded = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._row_sharding), batch)
        self._scan_cache[key] = sharded   # keep the sharded placement
        return sharded

    # -- dynamic filtering (batched collectives) -----------------------

    def apply_dynamic_filter(self, node: L.JoinNode, probe: Batch,
                             build: Batch) -> Batch:
        if not (self.enable_dynamic_filtering and
                self.mesh_dynamic_filtering):
            return probe
        if node.kind in ("anti", "left", "mark") or node.null_aware:
            return probe
        pairs = tuple(
            (pk, bk)
            for pk, bk in zip(node.left_keys, node.right_keys)
            if jnp.issubdtype(build.columns[bk].data.dtype, jnp.integer)
            and jnp.issubdtype(probe.columns[pk].data.dtype, jnp.integer))
        if not pairs:
            return probe
        keep, pruned = _batched_dynamic_filter(
            probe, build, tuple(p for p, _ in pairs),
            tuple(b for _, b in pairs))
        probe = probe.with_live(keep)
        pruned = self.fetch_ints(node, "dfpruned", pruned)[0]
        if pruned:
            self._note_pruned(pruned)
        if probe.capacity >= (1 << 16) and not self.chunk_mode:
            live = self.fetch_ints(node, "dflive",
                                   jnp.sum(probe.live))[0]
            new_cap = bucket_capacity(live)
            if new_cap * 4 <= probe.capacity:
                self.stats.dynamic_filter_compactions += 1
                probe = compact_batch(probe, new_cap)
        return probe

    def _note_pruned(self, pruned: int) -> None:
        from ..metrics import DYNAMIC_FILTER_ROWS_PRUNED
        self.stats.dynamic_filter_rows_pruned += pruned
        DYNAMIC_FILTER_ROWS_PRUNED.inc(pruned)

    # -- join distribution (broadcast vs partitioned) ------------------

    def run_multijoin(self, node):
        # The fused star kernel assumes a single-device VMEM-resident
        # build set; on a mesh the pairwise ladder keeps the
        # partitioned/broadcast machinery per hop instead.
        self._note_multijoin_degrade("mesh", len(node.dims))
        return self._run_multijoin_ladder(node)

    def _run_join_inner(self, node: L.JoinNode, probe: Batch,
                        build: Batch) -> Batch:
        mode = "partitioned" if self._partitioned_eligible(
            node, probe, build) else "broadcast"
        from ..metrics import JOIN_DISTRIBUTION_DECISIONS
        JOIN_DISTRIBUTION_DECISIONS.inc(mode=mode)
        self.strategy_decisions["JoinDistribution"] = mode
        if mode == "partitioned":
            out = self._mesh_partitioned_join(node, probe, build)
            if out is not None:
                return out
            # dup build keys or an unjoinable degrade: the replicated
            # ladder below handles it (expansion path included)
            self.strategy_decisions["JoinDistribution"] = "broadcast"
        return super()._run_join_inner(node, probe, build)

    def _partitioned_eligible(self, node: L.JoinNode, probe: Batch,
                              build: Batch) -> bool:
        """May this join hash-repartition over the mesh? The planner's
        stats gate asks for it (JoinNode.distribution, estimated build
        bytes vs broadcast_join_threshold_mb); the executor additionally
        requires the shape the per-shard kernel supports. Everything
        else broadcasts — that is today's replicated path, always
        correct."""
        if self.n_shards <= 1:
            return False
        if getattr(node, "distribution", "auto") != "partitioned":
            return False
        if node.kind != "inner" or node.residual is not None or \
                node.null_aware:
            return False
        if len(node.left_keys) != 1:
            # multi-key joins arrive here single-keyed via the packed
            # key column (Executor.pack_join_keys); a genuinely
            # multi-key shape cannot co-partition on one hash
            return False
        if self.hash_mode() == "off":
            return False
        for side, keys in ((probe, node.left_keys),
                           (build, node.right_keys)):
            for k in keys:
                if not jnp.issubdtype(side.columns[k].data.dtype,
                                      jnp.integer):
                    return False
        n_cols = len(probe.columns) + len(build.columns) + 2
        est = (probe.capacity + build.capacity) * self.n_shards * \
            8 * n_cols
        if est > self.MESH_EXCHANGE_BUDGET_BYTES:
            return False
        return True

    def _mesh_partitioned_join(self, node: L.JoinNode, probe: Batch,
                               build: Batch) -> Optional[Batch]:
        """The tentpole path: hash-repartition both sides over the mesh
        (splitmix64 fanout, all_to_all) and run the VMEM hash join
        per shard, with the dynamic-filter collectives batched into the
        same program. Returns None when the build broke the unique-key
        contract (caller expands on the replicated path)."""
        from ..metrics import MESH_REPARTITION_BYTES
        from ..ops import pallas_hash as ph
        from .stages import partitioned_hash_join_step
        n = self.n_shards
        probe = pad_to_multiple(probe, n)
        build = pad_to_multiple(build, n)
        # per-shard table sized for the 1/N key slice with 2x slack:
        # heavier skew escapes at runtime and degrades below, exactly
        # like a single-chip table overflow
        slots, _ = ph.join_table_slots(
            max(ph.MIN_TABLE_SLOTS, 2 * build.capacity // n))
        df = bool(self.enable_dynamic_filtering and
                  self.mesh_dynamic_filtering)
        skey = (n, node.left_keys, node.right_keys, node.kind, slots,
                probe.capacity, build.capacity, self.hash_mode(),
                self.gather_mode(), df)
        step = self._partitioned_steps.get(skey)
        if step is None:
            step = partitioned_hash_join_step(
                self.mesh, n, node.left_keys, node.right_keys,
                node.kind, slots, self.hash_mode(), self.gather_mode(),
                dynamic_filter=df)
            self._partitioned_steps[skey] = step
        out, dup, esc, pruned = step(self._shard_batch(probe),
                                     self._shard_batch(build))
        # exchange accounting (static estimate: each side moves its full
        # padded capacity once, data + valid + live planes)
        MESH_REPARTITION_BYTES.inc(
            probe.capacity * (len(probe.columns) * 9 + 1) +
            build.capacity * (len(build.columns) * 9 + 1))
        self.stats.hash_join_calls += 1
        self.stats.mesh_partitioned_joins += 1
        dup, esc, pruned = self.fetch_ints(
            node, f"meshjoin{slots}", dup, esc, pruned)
        if pruned:
            self._note_pruned(pruned)
        if esc > 0:
            # skewed partition overflowed its shard table: degrade to
            # the host equi-join over the same splitmix64 fanout (the
            # single-chip hybrid join's graceful path)
            self.stats.hash_join_escapes += 1
            host = self._partitioned_hash_join(node, probe, build)
            if host is None:
                return None
            self._note_strategy("JoinNode", "hybrid-hash", "join")
            return host
        if dup > 0:
            return None
        self._note_strategy("JoinNode", "hybrid-hash", "join")
        # the repartitioned output rides at n_shards x probe capacity
        # (the exchange's static bucket layout): compact by the fused
        # live count before anything downstream pays for the padding
        live = self.fetch_ints(node, "meshjoinlive",
                               jnp.sum(out.live))[0]
        return self.maybe_compact(out, live=live)
