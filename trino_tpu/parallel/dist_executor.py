"""Distributed plan executor: any logical plan over the device mesh.

Reference: the coordinator's planDistribution + worker task execution
(SqlQueryExecution.java:517, SURVEY.md §3.3) — a fragmented plan runs as
tasks on every worker, exchanging pages. TPU-native redesign (the
"How to Scale Your Model" recipe): keep the SINGLE global array program the
local executor already runs, place scan batches row-sharded over the mesh
(`NamedSharding(mesh, P('workers'))`), and let XLA's SPMD partitioner
insert the collectives a Trino cluster does by hand:

- masked group reductions    -> cross-shard psum      (= PARTIAL->FINAL agg)
- lax.sort for sort-groupby  -> distributed sort      (= hash repartition)
- join gathers               -> all_gather/all_to_all (= broadcast/
                                                         partitioned join)

The logical plan needs NO distributed rewrite: sharding is layout, not
semantics. Hand-tuned shard_map stage programs (parallel/stages.py) remain
the fast path for hot shapes; this executor is the general one — every SQL
feature the local executor supports runs distributed unchanged.

Scheduling note: one process drives the whole mesh (single-controller JAX),
so the coordinator/worker HTTP runtime (server/) carries control-plane
semantics (states, liveness, retries) while data-plane parallelism lives
in XLA collectives over ICI. That division is the core architectural
difference from the reference's page-shuttling workers.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch
from ..catalog import Catalog
from ..exec.executor import Executor
from ..planner import logical as L
from .mesh import AXIS, make_mesh


class MeshExecutor(Executor):
    """Executor whose scans land row-sharded on the mesh. Every operator
    kernel (already jitted) then runs as an SPMD program; XLA propagates
    shardings through the plan and inserts ICI collectives where global
    semantics require them."""

    def __init__(self, catalog: Catalog, mesh: Optional[Mesh] = None):
        super().__init__(catalog)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        # rows shard over every mesh axis (a 2-D hosts x chips mesh keeps
        # the inner collectives on ICI — see mesh.make_mesh_2d)
        self._row_sharding = NamedSharding(
            self.mesh, P(tuple(self.mesh.axis_names)))

    # Dynamic filtering's eager min/max over SHARDED build columns
    # dispatches a tiny cross-module all-reduce per probe; on the
    # virtual-CPU-device runtime those rendezvous intermittently
    # deadlock and XLA kills the process (rendezvous.cc "only 7 of 8
    # arrived", reproduced deterministically on TPC-DS q77). It is an
    # optimization, not semantics — pinned OFF on the mesh path (the
    # session rewires the flag from properties each query, hence a
    # set-proof property); the single-chip executor keeps it.
    @property
    def enable_dynamic_filtering(self):
        return False

    @enable_dynamic_filtering.setter
    def enable_dynamic_filtering(self, value):
        pass

    def run_scan(self, node: L.ScanNode) -> Batch:
        batch = super().run_scan(node)
        cap = batch.capacity
        if cap % self.n_shards != 0:
            return batch                  # tiny batch: stay single-device
        key = (node.catalog, node.schema_name, node.table,
               node.column_indices)
        sharded = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._row_sharding), batch)
        self._scan_cache[key] = sharded   # keep the sharded placement
        return sharded
