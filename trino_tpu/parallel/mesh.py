"""Device mesh runtime.

Reference: Trino's distribution machinery — NodePartitioningManager maps
partitions to worker nodes (sql/planner/NodePartitioningManager.java:60) and
stages run as tasks per node (SURVEY.md §2.8). Here the "worker fleet" is a
`jax.sharding.Mesh`; a stage is one jitted SPMD program laid over it with
`shard_map`, and inter-"task" data movement is an XLA collective over ICI
instead of HTTP page shuttling.

Axis naming: a single "workers" axis for row-sharded (DP-style) execution.
Multi-axis meshes (host x chip) layer on when multi-host lands.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch, Column

AXIS = "workers"


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map: newer jax exposes `jax.shard_map`
    (replication checking via check_vma), older releases only
    `jax.experimental.shard_map` (check_rep). Stage programs always
    disable the replication checker — collective-carrying bodies with
    manually asserted out_specs are exactly the case it rejects."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def pad_to_multiple(batch: Batch, multiple: int) -> Batch:
    """Grow a batch's capacity to the next multiple of `multiple` with
    dead rows (live=False, valid=False) so row-sharding divides evenly.
    Dead padding is invisible to every kernel (the live mask gates all
    semantics), so this is pure layout."""
    cap = batch.capacity
    pad = (-cap) % multiple
    if pad == 0:
        return batch
    cols = tuple(
        Column(data=jnp.pad(c.data, [(0, pad)] + [(0, 0)] *
                            (c.data.ndim - 1)),
               valid=jnp.pad(c.valid, (0, pad)))
        for c in batch.columns)
    return Batch(columns=cols, live=jnp.pad(batch.live, (0, pad)))


def make_mesh(n_devices: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def make_mesh_2d(n_hosts: int, chips_per_host: int,
                 axes=("hosts", "chips")) -> Mesh:
    """Two-axis mesh for multi-host topologies: the outer axis spans DCN
    (hosts), the inner axis ICI (chips within a host). Shardings laid out
    as P(('hosts','chips')) keep the heavy collectives on the inner axis —
    the scaling-book layout recipe, and the analog of Trino's node-level
    vs task-level parallelism split (SURVEY.md §2.8)."""
    devs = jax.devices()
    n = n_hosts * chips_per_host
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(n_hosts, chips_per_host), axes)


def shard_rows(batch: Batch, mesh: Mesh, axis: Optional[str] = None) -> Batch:
    """Place a host-built batch row-sharded across the mesh (the split
    assignment step: SourcePartitionedScheduler.assignSplits:378 analog).
    Multi-axis meshes shard rows over ALL axes (hosts x chips). Capacity
    must divide evenly — batch_from_numpy pads to 1024-multiples, so
    pad_multiple must be a multiple of mesh size * 8."""
    axes = (axis,) if axis is not None else tuple(mesh.axis_names)
    spec = NamedSharding(mesh, P(axes))

    def put(x):
        return jax.device_put(x, spec)

    return jax.tree_util.tree_map(put, batch)


def replicate(batch: Batch, mesh: Mesh) -> Batch:
    """Broadcast a (small) batch to every device — the
    FIXED_BROADCAST_DISTRIBUTION / BroadcastOutputBuffer path
    (execution/buffer/BroadcastOutputBuffer.java:56)."""
    spec = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), batch)
