"""Device mesh runtime.

Reference: Trino's distribution machinery — NodePartitioningManager maps
partitions to worker nodes (sql/planner/NodePartitioningManager.java:60) and
stages run as tasks per node (SURVEY.md §2.8). Here the "worker fleet" is a
`jax.sharding.Mesh`; a stage is one jitted SPMD program laid over it with
`shard_map`, and inter-"task" data movement is an XLA collective over ICI
instead of HTTP page shuttling.

Axis naming: a single "workers" axis for row-sharded (DP-style) execution.
Multi-axis meshes (host x chip) layer on when multi-host lands.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..batch import Batch, Column

AXIS = "workers"


def make_mesh(n_devices: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def make_mesh_2d(n_hosts: int, chips_per_host: int,
                 axes=("hosts", "chips")) -> Mesh:
    """Two-axis mesh for multi-host topologies: the outer axis spans DCN
    (hosts), the inner axis ICI (chips within a host). Shardings laid out
    as P(('hosts','chips')) keep the heavy collectives on the inner axis —
    the scaling-book layout recipe, and the analog of Trino's node-level
    vs task-level parallelism split (SURVEY.md §2.8)."""
    devs = jax.devices()
    n = n_hosts * chips_per_host
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(n_hosts, chips_per_host), axes)


def shard_rows(batch: Batch, mesh: Mesh, axis: Optional[str] = None) -> Batch:
    """Place a host-built batch row-sharded across the mesh (the split
    assignment step: SourcePartitionedScheduler.assignSplits:378 analog).
    Multi-axis meshes shard rows over ALL axes (hosts x chips). Capacity
    must divide evenly — batch_from_numpy pads to 1024-multiples, so
    pad_multiple must be a multiple of mesh size * 8."""
    axes = (axis,) if axis is not None else tuple(mesh.axis_names)
    spec = NamedSharding(mesh, P(axes))

    def put(x):
        return jax.device_put(x, spec)

    return jax.tree_util.tree_map(put, batch)


def replicate(batch: Batch, mesh: Mesh) -> Batch:
    """Broadcast a (small) batch to every device — the
    FIXED_BROADCAST_DISTRIBUTION / BroadcastOutputBuffer path
    (execution/buffer/BroadcastOutputBuffer.java:56)."""
    spec = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), batch)
