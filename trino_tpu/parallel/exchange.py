"""Collective exchange: the data plane, TPU edition.

Reference mapping (SURVEY.md §2.7/§2.8):

- hash repartition (PartitionedOutputOperator.java:48 ->
  partitioned OutputBuffer -> HTTP pull -> ExchangeOperator.java:44)
  ==> `lax.all_to_all` over ICI inside the jitted stage program
  (`repartition_by_key` below);
- broadcast build side (BroadcastOutputBuffer.java:56)
  ==> replicated sharding / `all_gather`;
- partial-aggregate merge at stage boundary (HashAggregationOperator
  PARTIAL on workers -> FINAL after exchange)
  ==> `lax.psum` / `pmin` / `pmax` on the dense group-state tables.

These run *inside* shard_map bodies. Static shapes force the bucket layout:
each shard sorts rows by destination and exchanges fixed-capacity buckets
(dead-row padding rides along); capacity per destination equals the local
capacity, so no row can overflow — the cost is n_shards x memory during the
exchange, to be tightened with two-pass sizing later (SURVEY.md §7 hard
part 1 trade-off, made explicit here).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..batch import Batch, Column
from .mesh import AXIS


def _hash64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer — the wire-partitioning hash
    (Trino: InterpretedHashGenerator / XxHash64 over channels)."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xbf58476d1ce4e5b9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94d049bb133111eb)
    x = x ^ (x >> 31)
    return x


def partition_of(key: jax.Array, n_parts: int) -> jax.Array:
    return (_hash64(key) % jnp.uint64(n_parts)).astype(jnp.int32)


def repartition_by_key(batch: Batch, key_index: int, n_shards: int,
                       axis: str = AXIS) -> Batch:
    """Inside shard_map: move every live row to shard
    hash(key) % n_shards. Output capacity = n_shards * local capacity.

    Algorithm (static shapes throughout):
    1. dest[i] = hash partition of row i (dead rows -> own shard, stay put
       as padding)
    2. sort rows by dest -> contiguous destination runs
    3. view as [n_shards, capacity] buckets, all_to_all over the mesh axis
    4. flatten received buckets; live mask survives the ride
    """
    cap = batch.capacity
    key_col = batch.columns[key_index]
    me = lax.axis_index(axis)
    dest = jnp.where(batch.live & key_col.valid,
                     partition_of(key_col.data.astype(jnp.int64), n_shards),
                     me)

    order = jax.lax.sort((dest, jnp.arange(cap, dtype=jnp.int32)),
                         num_keys=1)[1]
    dest_sorted = dest[order]
    # bucket (d, j) pulls the j-th row of destination-run d — a pure gather
    # (XLA TPU serializes scatters; gathers vectorize), dead-padded past
    # each run's end
    starts = jnp.searchsorted(dest_sorted, jnp.arange(n_shards))
    ends = jnp.searchsorted(dest_sorted, jnp.arange(n_shards), side="right")
    j = jnp.arange(cap)
    src = starts[:, None] + j[None, :]                    # [n_shards, cap]
    in_run = src < ends[:, None]
    src_c = jnp.clip(src, 0, cap - 1)

    def exchange(x, fill):
        x_sorted = x[order]
        buckets = jnp.where(in_run, x_sorted[src_c], fill)
        out = lax.all_to_all(buckets, axis, split_axis=0, concat_axis=0,
                             tiled=False)
        return out.reshape(n_shards * cap)

    new_cols = tuple(Column(data=exchange(c.data,
                                          jnp.zeros((), c.data.dtype)),
                            valid=exchange(c.valid, False))
                     for c in batch.columns)
    new_live = exchange(batch.live, False)
    return Batch(columns=new_cols, live=new_live)


def merge_partial_states(partial: Batch, agg_funcs: Tuple[str, ...],
                         n_keys: int, axis: str = AXIS) -> Batch:
    """Merge per-shard dense aggregate tables (direct strategy) into the
    final table, replicated on all shards. agg_funcs[i] names the i-th
    aggregate column's function (after n_keys key columns)."""
    # NB: only psum and all_gather here — the axon AOT compiler (and some
    # TPU lowering paths) support only Sum all-reduce; min/max merge rides
    # an all_gather + local reduce instead of pmin/pmax.
    cols = list(partial.columns)
    out_cols = []
    for i, col in enumerate(cols):
        if i < n_keys:
            out_cols.append(col)    # identical on every shard (decoded ids)
            continue
        func = agg_funcs[i - n_keys]
        if func in ("sum", "count", "count_star"):
            # invalid (empty-group) states hold 0, safe to sum directly
            data = lax.psum(col.data, axis)
        elif func in ("min", "max"):
            if jnp.issubdtype(col.data.dtype, jnp.integer):
                ident = jnp.iinfo(col.data.dtype).max if func == "min" \
                    else jnp.iinfo(col.data.dtype).min
            else:
                ident = jnp.inf if func == "min" else -jnp.inf
            masked = jnp.where(col.valid, col.data, ident)
            gathered = lax.all_gather(masked, axis)   # [n_shards, cap]
            data = (jnp.min if func == "min" else jnp.max)(gathered, axis=0)
        else:
            raise ValueError(func)
        valid = lax.psum(col.valid.astype(jnp.int32), axis) > 0
        out_cols.append(Column(data=data, valid=valid))
    live = lax.psum(partial.live.astype(jnp.int32), axis) > 0
    # key validity should reflect merged liveness
    out_cols[:n_keys] = [Column(data=c.data, valid=live)
                         for c in out_cols[:n_keys]]
    return Batch(columns=tuple(out_cols), live=live)
