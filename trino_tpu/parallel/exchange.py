"""Collective exchange: the data plane, TPU edition.

Reference mapping (SURVEY.md §2.7/§2.8):

- hash repartition (PartitionedOutputOperator.java:48 ->
  partitioned OutputBuffer -> HTTP pull -> ExchangeOperator.java:44)
  ==> `lax.all_to_all` over ICI inside the jitted stage program
  (`repartition_by_key` below);
- broadcast build side (BroadcastOutputBuffer.java:56)
  ==> replicated sharding / `all_gather`;
- partial-aggregate merge at stage boundary (HashAggregationOperator
  PARTIAL on workers -> FINAL after exchange)
  ==> `lax.psum` / `pmin` / `pmax` on the dense group-state tables.

These run *inside* shard_map bodies. Static shapes force the bucket layout:
each shard sorts rows by destination and exchanges fixed-capacity buckets
(dead-row padding rides along); capacity per destination equals the local
capacity, so no row can overflow — the cost is n_shards x memory during the
exchange, to be tightened with two-pass sizing later (SURVEY.md §7 hard
part 1 trade-off, made explicit here).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..batch import Batch, Column
from .mesh import AXIS


def _hash64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer — the wire-partitioning hash
    (Trino: InterpretedHashGenerator / XxHash64 over channels)."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xbf58476d1ce4e5b9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94d049bb133111eb)
    x = x ^ (x >> 31)
    return x


def partition_of(key: jax.Array, n_parts: int) -> jax.Array:
    return (_hash64(key) % jnp.uint64(n_parts)).astype(jnp.int32)


def repartition_by_key(batch: Batch, key_index: int, n_shards: int,
                       axis: str = AXIS) -> Batch:
    """Inside shard_map: move every live row to shard
    hash(key) % n_shards. Output capacity = n_shards * local capacity.

    Algorithm (static shapes throughout):
    1. dest[i] = hash partition of row i (dead rows -> own shard, stay put
       as padding)
    2. sort rows by dest -> contiguous destination runs
    3. view as [n_shards, capacity] buckets, all_to_all over the mesh axis
    4. flatten received buckets; live mask survives the ride
    """
    cap = batch.capacity
    key_col = batch.columns[key_index]
    me = lax.axis_index(axis)
    dest = jnp.where(batch.live & key_col.valid,
                     partition_of(key_col.data.astype(jnp.int64), n_shards),
                     me)

    order = jax.lax.sort((dest, jnp.arange(cap, dtype=jnp.int32)),
                         num_keys=1)[1]
    dest_sorted = dest[order]
    # bucket (d, j) pulls the j-th row of destination-run d — a pure gather
    # (XLA TPU serializes scatters; gathers vectorize), dead-padded past
    # each run's end
    starts = jnp.searchsorted(dest_sorted, jnp.arange(n_shards))
    ends = jnp.searchsorted(dest_sorted, jnp.arange(n_shards), side="right")
    j = jnp.arange(cap)
    src = starts[:, None] + j[None, :]                    # [n_shards, cap]
    in_run = src < ends[:, None]
    src_c = jnp.clip(src, 0, cap - 1)

    def exchange(x, fill):
        x_sorted = x[order]
        buckets = jnp.where(in_run, x_sorted[src_c], fill)
        out = lax.all_to_all(buckets, axis, split_axis=0, concat_axis=0,
                             tiled=False)
        return out.reshape(n_shards * cap)

    new_cols = tuple(Column(data=exchange(c.data,
                                          jnp.zeros((), c.data.dtype)),
                            valid=exchange(c.valid, False))
                     for c in batch.columns)
    new_live = exchange(batch.live, False)
    return Batch(columns=new_cols, live=new_live)


def join_filter_bounds(build: Batch, build_keys: Tuple[int, ...],
                       axis: str = AXIS):
    """Global [min, max] per build key, computed INSIDE the sharded
    stage body — the batched form of dynamic filtering. The old mesh
    path fetched per-key bounds eagerly, dispatching one tiny
    cross-module all-reduce per probe; those independent rendezvous
    deadlock intermittently on the virtual-device runtime (TPC-DS q77).
    Here every key's (min, -max) rides ONE all_gather in the SAME
    program as the join, so there is no mid-execution rendezvous to
    miss. The sign flip is the line-102 idiom above: min(-x) = -max(x),
    one local reduce shape serves both bounds through the sum-only /
    all_gather collective contract."""
    imax = jnp.iinfo(jnp.int64).max
    stats = []
    for bk_i in build_keys:
        col = build.columns[bk_i]
        m = build.live & col.valid
        d = col.data.astype(jnp.int64)
        stats.append(jnp.min(jnp.where(m, d, imax)))
        stats.append(jnp.min(jnp.where(m, -d, imax)))
    gathered = lax.all_gather(jnp.stack(stats), axis)   # [n_shards, 2K]
    merged = jnp.min(gathered, axis=0)
    kmins = merged[0::2]
    kmaxs = -merged[1::2]
    return kmins, kmaxs


def apply_filter_bounds(probe: Batch, probe_keys: Tuple[int, ...],
                        kmins, kmaxs) -> Tuple[Batch, jax.Array]:
    """Prune probe rows whose key falls outside the build's [min, max]
    (per key pair, all inside the stage program). Returns the filtered
    batch and the local pruned-row count (caller psums it into the
    dynamic_filter_rows_pruned metric). NULL keys stay live — they are
    dropped by join semantics, not by the filter."""
    keep = probe.live
    for j, pk_i in enumerate(probe_keys):
        col = probe.columns[pk_i]
        d = col.data.astype(jnp.int64)
        keep = keep & (~col.valid | ((d >= kmins[j]) & (d <= kmaxs[j])))
    pruned = jnp.sum(probe.live, dtype=jnp.int64) - \
        jnp.sum(keep, dtype=jnp.int64)
    return probe.with_live(keep), pruned


def merge_partial_states(partial: Batch, agg_funcs: Tuple[str, ...],
                         n_keys: int, axis: str = AXIS) -> Batch:
    """Merge per-shard dense aggregate tables (direct strategy) into the
    final table, replicated on all shards. agg_funcs[i] names the i-th
    aggregate column's function (after n_keys key columns)."""
    # NB: only psum and all_gather here — the axon AOT compiler (and some
    # TPU lowering paths) support only Sum all-reduce; min/max merge rides
    # an all_gather + local reduce instead of pmin/pmax.
    cols = list(partial.columns)
    out_cols = []
    for i, col in enumerate(cols):
        if i < n_keys:
            out_cols.append(col)    # identical on every shard (decoded ids)
            continue
        func = agg_funcs[i - n_keys]
        if func in ("sum", "count", "count_star"):
            # invalid (empty-group) states hold 0, safe to sum directly
            data = lax.psum(col.data, axis)
        elif func in ("min", "max"):
            if jnp.issubdtype(col.data.dtype, jnp.integer):
                ident = jnp.iinfo(col.data.dtype).max if func == "min" \
                    else jnp.iinfo(col.data.dtype).min
            else:
                ident = jnp.inf if func == "min" else -jnp.inf
            masked = jnp.where(col.valid, col.data, ident)
            gathered = lax.all_gather(masked, axis)   # [n_shards, cap]
            data = (jnp.min if func == "min" else jnp.max)(gathered, axis=0)
        else:
            raise ValueError(func)
        valid = lax.psum(col.valid.astype(jnp.int32), axis) > 0
        out_cols.append(Column(data=data, valid=valid))
    live = lax.psum(partial.live.astype(jnp.int32), axis) > 0
    # key validity should reflect merged liveness
    out_cols[:n_keys] = [Column(data=c.data, valid=live)
                         for c in out_cols[:n_keys]]
    return Batch(columns=tuple(out_cols), live=live)
