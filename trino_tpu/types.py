"""Type system for trino_tpu.

Role of the reference's ``core/trino-spi`` type system (spi/type/Type.java,
82 files): a fixed set of SQL logical types with a defined physical layout.
Our physical layout is chosen for TPU/XLA rather than the JVM:

- BIGINT / INTEGER      -> int64 / int32 arrays
- DOUBLE                -> float64 (SQL double semantics: discrete
                           functions like ceil/floor must not jump on f32
                           rounding error; XLA emulates f64 on the TPU VPU
                           — acceptable since hot aggregation arithmetic is
                           scaled-int64 decimal, not double)
- BOOLEAN               -> bool arrays
- DATE                  -> int32 days since 1970-01-01 (same as Trino)
- DECIMAL(p, s)         -> int64 scaled by 10**s (Trino short decimal,
                           spi/type/DecimalType.java); sums widened per
                           ops/aggregate.py's accumulator policy
- VARCHAR               -> int32 dictionary codes into a host-side string
                           pool (Trino's DictionaryBlock generalized into
                           the storage policy, spi/block/DictionaryBlock.java)

Nullability is carried out-of-band as a per-column validity mask (Trino:
per-block null mask, spi/block/Block.java). Three-valued logic lives in
ops/project.py.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

import numpy as np


class TypeKind(enum.Enum):
    BIGINT = "bigint"
    INTEGER = "integer"
    DOUBLE = "double"
    BOOLEAN = "boolean"
    DATE = "date"
    TIMESTAMP = "timestamp"
    DECIMAL = "decimal"
    VARCHAR = "varchar"
    ARRAY = "array"


@dataclass(frozen=True)
class DataType:
    """A SQL logical type. Hashable so schemas can key jit caches."""

    kind: TypeKind
    precision: Optional[int] = None  # DECIMAL only
    scale: Optional[int] = None      # DECIMAL only
    element: Optional["DataType"] = None   # ARRAY only

    def __post_init__(self):
        if self.kind is TypeKind.DECIMAL:
            assert self.precision is not None and self.scale is not None
            # p <= 18 columns store int64 unscaled values directly;
            # 18 < p <= 38 (Int128 territory in the reference,
            # spi/type/Int128.java) arises from aggregate RESULT types —
            # sums accumulate in two int64 limbs on device and combine
            # exactly while |total| < 2^63 (raises at the type level
            # beyond 38 digits like the reference's overflow checks)
            assert self.precision <= 38, \
                "decimals beyond 38 digits unsupported"
        if self.kind is TypeKind.ARRAY:
            assert self.element is not None

    # ---- physical layout ------------------------------------------------

    @property
    def np_dtype(self) -> np.dtype:
        return {
            TypeKind.BIGINT: np.dtype(np.int64),
            TypeKind.INTEGER: np.dtype(np.int32),
            TypeKind.DOUBLE: np.dtype(np.float64),
            TypeKind.BOOLEAN: np.dtype(np.bool_),
            TypeKind.DATE: np.dtype(np.int32),
            TypeKind.TIMESTAMP: np.dtype(np.int64),   # micros since epoch
            TypeKind.DECIMAL: np.dtype(np.int64),
            TypeKind.VARCHAR: np.dtype(np.int32),  # dictionary codes
            # arrays follow the dictionary discipline: the device carries
            # int32 pool ids, element tuples live host-side in the Field
            # (offsets+flat-values device layout is the escape hatch once
            # array-heavy kernels become hot; today's consumers — UNNEST,
            # cardinality, element access — run at batch edges)
            TypeKind.ARRAY: np.dtype(np.int32),
        }[self.kind]

    @property
    def is_dictionary(self) -> bool:
        return self.kind in (TypeKind.VARCHAR, TypeKind.ARRAY)

    @property
    def is_integerlike(self) -> bool:
        return self.kind in (TypeKind.BIGINT, TypeKind.INTEGER, TypeKind.DATE,
                             TypeKind.TIMESTAMP, TypeKind.DECIMAL,
                             TypeKind.VARCHAR)

    def __repr__(self) -> str:
        if self.kind is TypeKind.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        return self.kind.value


BIGINT = DataType(TypeKind.BIGINT)
INTEGER = DataType(TypeKind.INTEGER)
DOUBLE = DataType(TypeKind.DOUBLE)
BOOLEAN = DataType(TypeKind.BOOLEAN)
DATE = DataType(TypeKind.DATE)
TIMESTAMP = DataType(TypeKind.TIMESTAMP)
VARCHAR = DataType(TypeKind.VARCHAR)


def decimal(precision: int, scale: int) -> DataType:
    return DataType(TypeKind.DECIMAL, precision, scale)


def array_of(element: DataType) -> DataType:
    return DataType(TypeKind.ARRAY, element=element)


def common_super_type(a: DataType, b: DataType) -> DataType:
    """Result type of arithmetic coercion between two types.

    Mirrors the spirit of Trino's TypeCoercion (sql/analyzer/TypeCoercion.java)
    for the subset of types we support.
    """
    if a == b:
        return a
    kinds = {a.kind, b.kind}
    if TypeKind.DOUBLE in kinds:
        return DOUBLE
    if a.kind is TypeKind.DECIMAL and b.kind is TypeKind.DECIMAL:
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return decimal(min(18, intd + scale), scale)
    if TypeKind.DECIMAL in kinds:
        d = a if a.kind is TypeKind.DECIMAL else b
        return d
    if kinds == {TypeKind.BIGINT, TypeKind.INTEGER}:
        return BIGINT
    if TypeKind.DATE in kinds and kinds & {TypeKind.BIGINT, TypeKind.INTEGER}:
        return DATE  # date +/- integer days
    if kinds == {TypeKind.TIMESTAMP, TypeKind.DATE}:
        return TIMESTAMP
    if TypeKind.TIMESTAMP in kinds and \
            kinds & {TypeKind.BIGINT, TypeKind.INTEGER}:
        return TIMESTAMP
    raise TypeError(f"no common type for {a} and {b}")
