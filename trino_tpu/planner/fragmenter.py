"""Plan fragmenter: cut a logical plan into a tree of stages.

Reference: PlanFragmenter (sql/planner/PlanFragmenter.java:126) cuts the
plan at exchange boundaries into PlanFragments; PhasedExecutionSchedule
(execution/scheduler/PhasedExecutionSchedule.java:81) orders them so join
build sides complete before their probes start.

TPU shape: the probe spine (driver fact-table scan up to the root) stays
one fragment — it is the chunk/split-streamed pipeline. Every *heavy* join
build side becomes its own fragment, cut at a RemoteSourceNode. Build
fragments schedule bottom-up (phased); each one's materialized output is
broadcast into its consumer (Trino's REPLICATED distribution — the right
default on a TPU mesh, where the build must be device-resident on every
chip anyway; per-chip-partitioned builds ride the in-jit all_to_all path in
parallel/stages.py instead of this runtime).

"Heavy" = the subtree does real work: contains a join/aggregate/window, or
scans >= min_build_rows rows. Light builds (nation, region) stay inline in
the consumer fragment — shipping 25 rows is cheaper than a stage round
trip, the same reasoning as Trino's broadcast-small-table rule
(DetermineJoinDistributionType.java:51).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from . import logical as L


@dataclass
class Fragment:
    """One schedulable unit (PlanFragment's role)."""
    id: int
    root: L.PlanNode               # contains RemoteSourceNodes for deps
    depends_on: Tuple[int, ...]    # producer fragment ids
    partitioning: str              # 'broadcast' (build) | 'source' (probe
    #                                spine + root: split-streamed)
    est_rows: int = 0              # largest scan in the fragment


def _subtree_nodes(node: L.PlanNode):
    yield node
    for c in L.children(node):
        yield from _subtree_nodes(c)


def _scan_rows(catalog, s: L.ScanNode) -> int:
    try:
        return catalog.get_table(s.catalog, s.schema_name, s.table).num_rows
    except Exception:            # noqa: BLE001 — stats probe only
        return 0


def _is_heavy(node: L.PlanNode, catalog, min_build_rows: int) -> bool:
    for n in _subtree_nodes(node):
        if isinstance(n, (L.JoinNode, L.AggregateNode, L.WindowNode)):
            return True
        if isinstance(n, L.ScanNode) and \
                _scan_rows(catalog, n) >= min_build_rows:
            return True
    return False


def fragment_plan(root: L.OutputNode, catalog,
                  min_build_rows: int = 100_000) -> List[Fragment]:
    """Cut heavy join build sides into fragments. Returns fragments in
    dependency (phased) order; the last entry is the root fragment whose
    tree contains RemoteSourceNodes for every other fragment."""
    import dataclasses as _dc

    frags: List[Fragment] = []
    counter = [0]

    def rewrite(node: L.PlanNode) -> Tuple[L.PlanNode, Tuple[int, ...]]:
        """Top-down rebuild; returns (rewritten node, direct fragment
        deps). A heavy join build side is cut here and NOT re-traversed
        by its consumer — its own heavy builds were cut in the recursion,
        so deep join trees produce deep stage trees."""
        if isinstance(node, L.JoinNode) and \
                _is_heavy(node.right, catalog, min_build_rows):
            left, dl = rewrite(node.left)
            sub_root, sub_deps = rewrite(node.right)
            counter[0] += 1
            fid = counter[0]
            est = max((_scan_rows(catalog, s)
                       for s in _subtree_nodes(sub_root)
                       if isinstance(s, L.ScanNode)), default=0)
            frags.append(Fragment(fid, sub_root, sub_deps, "broadcast",
                                  est))
            right = L.RemoteSourceNode(fid, node.right.output)
            return _dc.replace(node, left=left, right=right), dl + (fid,)
        deps: Tuple[int, ...] = ()
        changes = {}
        for f in _dc.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, L.PlanNode):
                nv, d = rewrite(v)
                deps += d
                if nv is not v:
                    changes[f.name] = nv
        return (_dc.replace(node, **changes) if changes else node), deps

    new_root, deps = rewrite(root)
    counter[0] += 1
    est = max((_scan_rows(catalog, s) for s in _subtree_nodes(new_root)
               if isinstance(s, L.ScanNode)), default=0)
    frags.append(Fragment(counter[0], new_root, deps, "source", est))
    return frags


def explain_fragments(frags: List[Fragment]) -> str:
    """Distributed-plan rendering (PlanPrinter.textDistributedPlan)."""
    out = []
    for f in frags:
        deps = f" <- fragments {list(f.depends_on)}" if f.depends_on else ""
        out.append(f"Fragment {f.id} [{f.partitioning}]{deps}")
        out.append(L.explain_text(f.root, indent=1))
    return "\n".join(out)
