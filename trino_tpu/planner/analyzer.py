"""Analyzer: scopes, name resolution, and AST -> typed IR lowering.

Reference: Trino splits this across Analyzer/ExpressionAnalyzer
(sql/analyzer/Analyzer.java:47) producing an Analysis consumed by
LogicalPlanner. We fuse analysis into planning (planner.py) and keep here
the scope machinery and expression lowering, including the
dictionary-predicate lowering that replaces Trino's LikeMatcher and slice
comparisons for VARCHAR (strings never reach the device; SURVEY.md §7).
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .. import ir
from ..batch import Field
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, VARCHAR, DataType,
                     TypeKind, common_super_type, decimal)
from ..sql import ast_nodes as A

EPOCH = datetime.date(1970, 1, 1)


class AnalysisError(Exception):
    pass


@dataclass
class ScopeColumn:
    qualifier: Optional[str]      # table alias (lower-case)
    name: str                     # column name (lower-case)
    dtype: DataType
    index: int                    # position in the relation's output
    field: Optional[Field] = None  # carries dictionary for VARCHAR


class Scope:
    def __init__(self, columns: List[ScopeColumn]):
        self.columns = columns

    def resolve(self, parts: Tuple[str, ...]) -> ScopeColumn:
        parts = tuple(p.lower() for p in parts)
        if len(parts) == 1:
            matches = [c for c in self.columns if c.name == parts[0]]
        elif len(parts) == 2:
            matches = [c for c in self.columns
                       if c.qualifier == parts[0] and c.name == parts[1]]
        else:
            raise AnalysisError(f"unsupported name {'.'.join(parts)}")
        if not matches:
            raise AnalysisError(f"column '{'.'.join(parts)}' not found")
        if len(matches) > 1:
            raise AnalysisError(f"column '{'.'.join(parts)}' is ambiguous")
        return matches[0]

    def try_resolve(self, parts) -> Optional[ScopeColumn]:
        try:
            return self.resolve(parts)
        except AnalysisError:
            return None


AGG_NAMES = {"sum", "avg", "count", "min", "max",
             # variance family decomposes to sum/sum-of-squares/count with
             # a post-aggregation finalizer (AccumulatorCompiler's
             # VarianceState, operator/aggregation/VarianceAggregation)
             "stddev", "stddev_samp", "stddev_pop",
             "variance", "var_samp", "var_pop",
             # approx_distinct computes the EXACT distinct count through
             # the sort kernel's dedup — on TPU the sort network makes
             # exactness cheaper than per-group HLL register scatters,
             # and 0% error is within the reference's 2.3% contract
             # (ApproximateCountDistinctAggregation)
             "approx_distinct",
             "bool_and", "bool_or", "every"}

VARIANCE_AGGS = {"stddev", "stddev_samp", "stddev_pop",
                 "variance", "var_samp", "var_pop"}


def contains_aggregate(node: A.Node) -> bool:
    if isinstance(node, A.WindowFunc):
        # the window call itself is not an aggregation, but aggregates may
        # appear in its args (sum(sum(x)) OVER ..) or its OVER clause
        # (rank() OVER (ORDER BY sum(x)))
        return any(contains_aggregate(c) for c in ast_children(node))
    if isinstance(node, A.FunctionCall) and node.name in AGG_NAMES:
        return True
    for child in ast_children(node):
        if contains_aggregate(child):
            return True
    return False


def ast_children(node: A.Node):
    if isinstance(node, A.BinaryOp):
        return (node.left, node.right)
    if isinstance(node, A.UnaryOp):
        return (node.arg,)
    if isinstance(node, (A.IsNullPredicate,)):
        return (node.arg,)
    if isinstance(node, A.BetweenPredicate):
        return (node.arg, node.low, node.high)
    if isinstance(node, A.InPredicate):
        return (node.arg,) + node.values
    if isinstance(node, A.LikePredicate):
        return (node.arg, node.pattern)
    if isinstance(node, A.FunctionCall):
        return node.args
    if isinstance(node, A.WindowFunc):
        return node.args + node.partition_by + \
            tuple(o.expr for o in node.order_by)
    if isinstance(node, A.CastExpr):
        return (node.arg,)
    if isinstance(node, A.ExtractExpr):
        return (node.arg,)
    if isinstance(node, A.CaseExpr):
        out = [] if node.operand is None else [node.operand]
        for c, v in node.whens:
            out += [c, v]
        if node.default is not None:
            out.append(node.default)
        return tuple(out)
    return ()


# --------------------------------------------------------------------------
# literal typing & constant folding
# --------------------------------------------------------------------------

def number_literal(text: str) -> ir.Literal:
    if "." not in text:
        return ir.Literal(int(text), BIGINT)
    intpart, frac = text.split(".")
    scale = len(frac)
    digits = (intpart + frac).lstrip("0") or "0"
    value = int(intpart + frac) if intpart + frac else 0
    return ir.Literal(value, decimal(max(len(digits), 1), scale))


def date_literal(iso: str) -> ir.Literal:
    d = datetime.date.fromisoformat(iso)
    return ir.Literal((d - EPOCH).days, DATE)


def timestamp_literal(text: str) -> ir.Literal:
    from ..types import TIMESTAMP
    dt = datetime.datetime.fromisoformat(text)
    epoch = datetime.datetime(1970, 1, 1)
    micros = int((dt - epoch).total_seconds() * 1_000_000)
    return ir.Literal(micros, TIMESTAMP)


def add_months(d: datetime.date, n: int) -> datetime.date:
    y, m0 = divmod(d.year * 12 + d.month - 1 + n, 12)
    last = [31, 29 if y % 4 == 0 and (y % 100 != 0 or y % 400 == 0) else 28,
            31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m0]
    return datetime.date(y, m0 + 1, min(d.day, last))


def fold_date_interval(base_days: int, interval: A.IntervalLit,
                       subtract: bool) -> int:
    n = -interval.value if (interval.negative != subtract) else interval.value
    base = EPOCH + datetime.timedelta(days=base_days)
    if interval.unit == "day":
        return base_days + n
    months = n * (12 if interval.unit == "year" else 1)
    return (add_months(base, months) - EPOCH).days


# --------------------------------------------------------------------------
# LIKE -> regex over dictionary pool
# --------------------------------------------------------------------------

def like_to_regex(pattern: str, escape: Optional[str]) -> re.Pattern:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("".join(out), re.DOTALL)


# --------------------------------------------------------------------------
# expression lowering
# --------------------------------------------------------------------------

class ExpressionLowerer:
    """Lowers an AST expression (no aggregates) to typed IR over a scope.

    `planner` (optional) enables uncorrelated scalar subquery lowering:
    the subquery is planned independently and embedded as a
    ScalarSubqueryRef the executor folds to a constant. Correlated
    subqueries fail to plan here and are handled by the planner's
    subquery-predicate pass (decorrelation to joins)."""

    def __init__(self, scope: Scope, planner=None, window_slots=None):
        self.scope = scope
        self.planner = planner
        # keep the caller's dict object: plan_aggregation populates it
        # after constructing the lowerer
        self.window_slots = window_slots if window_slots is not None else {}

    def lower(self, node: A.Node) -> ir.Expr:
        if isinstance(node, A.WindowFunc):
            slot = self.window_slots.get(node)
            if slot is None:
                raise AnalysisError(
                    f"window function {node.name}() not allowed here")
            return slot
        if isinstance(node, A.Identifier):
            col = self.scope.resolve(node.parts)
            return ir.ColumnRef(col.index, col.dtype, col.name)
        if isinstance(node, A.NumberLit):
            return number_literal(node.text)
        if isinstance(node, A.StringLit):
            # bare string literal: only meaningful against dictionary
            # columns; handled contextually below. Standalone -> error when
            # it reaches device lowering.
            return _StringConst(node.value)
        if isinstance(node, A.BoolLit):
            return ir.Literal(node.value, BOOLEAN)
        if isinstance(node, A.NullLit):
            return ir.Literal(None, BIGINT)
        if isinstance(node, A.DateLit):
            return date_literal(node.value)
        if isinstance(node, A.TimestampLit):
            return timestamp_literal(node.value)
        if isinstance(node, A.IntervalLit):
            raise AnalysisError(
                "INTERVAL literal only supported in date +/- INTERVAL")
        if isinstance(node, A.ArrayLiteral):
            return self.lower_array_literal(node)

        if isinstance(node, A.BinaryOp):
            return self.lower_binary(node)
        if isinstance(node, A.UnaryOp):
            if node.op == "not":
                return ir.Not(self.to_bool(self.lower(node.arg)))
            arg = self.lower(node.arg)
            if node.op == "-":
                if isinstance(arg, ir.Literal):
                    return ir.Literal(-arg.value if arg.value is not None
                                      else None, arg.dtype)
                return ir.Negate(arg, arg.dtype)
            return arg

        if isinstance(node, A.IsNullPredicate):
            return ir.IsNull(self.lower(node.arg), negated=node.negated)

        if isinstance(node, A.BetweenPredicate):
            arg = self.lower(node.arg)
            low = self.lower(node.low)
            high = self.lower(node.high)
            if arg.dtype.kind is TypeKind.VARCHAR and (
                    isinstance(low, _StringConst) or
                    isinstance(high, _StringConst)):
                pred = self.dict_range(arg, low, high)
            else:
                low = self.coerce_const(low, arg)
                high = self.coerce_const(high, arg)
                pred = ir.Between(arg, low, high)
            return ir.Not(pred) if node.negated else pred

        if isinstance(node, A.InPredicate):
            arg = self.lower(node.arg)
            vals = [self.lower(v) for v in node.values]
            if arg.dtype.kind is TypeKind.VARCHAR:
                if not all(isinstance(v, _StringConst) for v in vals):
                    raise AnalysisError("IN on varchar requires string "
                                        "literals")
                strings = {v.value for v in vals}   # duplicates are fine
                pred = self.dict_lut(arg, lambda s: s in strings)
            else:
                lits = []
                for v in vals:
                    v = self.coerce_const(v, arg)
                    if not isinstance(v, ir.Literal):
                        raise AnalysisError("IN requires literal values")
                    lits.append(v)
                pred = ir.InList(arg, tuple(lits))
            return ir.Not(pred) if node.negated else pred

        if isinstance(node, A.LikePredicate):
            arg = self.lower(node.arg)
            if arg.dtype.kind is not TypeKind.VARCHAR:
                raise AnalysisError("LIKE requires a varchar argument")
            if not isinstance(node.pattern, A.StringLit):
                raise AnalysisError("LIKE pattern must be a literal")
            escape = None
            if node.escape is not None:
                if not isinstance(node.escape, A.StringLit):
                    raise AnalysisError("ESCAPE must be a literal")
                escape = node.escape.value
            rx = like_to_regex(node.pattern.value, escape)
            pred = self.dict_lut(arg, lambda s: rx.fullmatch(s) is not None)
            return ir.Not(pred) if node.negated else pred

        if isinstance(node, A.CaseExpr):
            return self.lower_case(node)

        if isinstance(node, A.CastExpr):
            arg = self.lower(node.arg)
            target = parse_type(node.type_name)
            if isinstance(arg, _StringConst):
                return self.cast_string_const(arg, target)
            return ir.Cast(arg, target)

        if isinstance(node, A.ExtractExpr):
            arg = self.lower(node.arg)
            if arg.dtype.kind not in (TypeKind.DATE, TypeKind.TIMESTAMP):
                raise AnalysisError(
                    "EXTRACT requires a date or timestamp argument")
            if node.part in ("hour", "minute", "second") and \
                    arg.dtype.kind is not TypeKind.TIMESTAMP:
                raise AnalysisError(
                    f"EXTRACT({node.part}) requires a timestamp")
            return ir.ExtractField(node.part, arg)

        if isinstance(node, A.FunctionCall):
            if node.name in AGG_NAMES:
                raise AnalysisError(
                    f"aggregate {node.name}() not allowed here")
            if node.name in ("substring", "substr"):
                return self.lower_substring(node)
            return self.lower_scalar_func(node)

        if isinstance(node, A.InSubquery):
            # non-conjunct position (inside OR / select item): plan the
            # uncorrelated subquery now, fold to InList at execution
            # (conjunct-position IN decorrelates to semi/anti joins before
            # lowering ever sees it)
            if self.planner is None:
                raise AnalysisError(
                    "IN subquery not allowed in this context")
            arg = self.lower(node.arg)
            sub = self.planner.plan_query(node.query)  # raises if correlated
            if len(sub.scope.columns) != 1:
                raise AnalysisError("IN subquery must return one column")
            arg_field = self.planner.field_for(arg, self.scope)
            ref = ir.InSubqueryRef(arg, sub.node, arg_field,
                                   sub.scope.columns[0].field)
            return ir.Not(ref) if node.negated else ref

        if isinstance(node, A.ScalarSubquery):
            if self.planner is None:
                raise AnalysisError(
                    "scalar subquery not allowed in this context")
            sub = self.planner.plan_query(node.query)   # raises if correlated
            if len(sub.scope.columns) != 1:
                raise AnalysisError("scalar subquery must return one column")
            return ir.ScalarSubqueryRef(sub.node, sub.scope.columns[0].dtype)

        raise AnalysisError(f"unsupported expression {type(node).__name__}")

    def lower_substring(self, node: A.FunctionCall) -> ir.Expr:
        """substring(varchar_col, start, length): transform the string pool
        host-side; device codes are unchanged (DerivedDict)."""
        if len(node.args) != 3:
            raise AnalysisError("substring(col, start, length) expected")
        arg = self.lower(node.args[0])
        if arg.dtype.kind is not TypeKind.VARCHAR:
            raise AnalysisError("substring requires a varchar argument")
        try:
            start = int(node.args[1].text)
            length = int(node.args[2].text)
        except (AttributeError, ValueError):
            raise AnalysisError("substring start/length must be integers")
        pool = self.pool_of(arg)
        transformed = [s[start - 1:start - 1 + length] for s in pool]
        new_pool = tuple(sorted(set(transformed)))
        index = {s: i for i, s in enumerate(new_pool)}
        lut = tuple(index[s] for s in transformed)
        return ir.DerivedDict(arg, lut, new_pool, arg.dtype)

    def lower_array_literal(self, node: "A.ArrayLiteral") -> ir.Expr:
        """ARRAY[...] of constants -> pool entry (tree/ArrayConstructor).
        Elements must be literals; NULL elements allowed."""
        from ..types import array_of
        elems = []
        elem_t = None
        for item in node.items:
            e = self.lower(item)
            if isinstance(e, _StringConst):
                elems.append(e.value)
                et = VARCHAR
            elif isinstance(e, ir.Literal):
                elems.append(e.value)
                et = e.dtype
            else:
                raise AnalysisError(
                    "ARRAY[...] elements must be constants")
            if e_is_null := (elems[-1] is None):
                continue
            if elem_t is None or elem_t.kind is TypeKind.BIGINT:
                elem_t = et
            elif et.kind is not TypeKind.BIGINT and et != elem_t:
                elem_t = common_super_type(elem_t, et)
        if elem_t is None:
            elem_t = BIGINT
        return ir.ArrayConst((tuple(elems),), array_of(elem_t))

    def lower_scalar_func(self, node: A.FunctionCall) -> ir.Expr:
        """Built-in scalar functions (metadata/InternalFunctionBundle.java's
        registry role): numeric ones lower to ir.ScalarFunc, varchar ones to
        host-side dictionary-pool transforms."""
        name = node.name
        args = [self.lower(a) for a in node.args]

        # -- varchar functions: pool transforms / LUTs --------------------
        if name in ("upper", "lower", "trim", "ltrim", "rtrim"):
            if len(args) != 1:
                raise AnalysisError(f"{name} takes one argument")
            fn = {"upper": str.upper, "lower": str.lower,
                  "trim": str.strip, "ltrim": str.lstrip,
                  "rtrim": str.rstrip}[name]
            return self.dict_transform(args[0], fn)
        if name == "length":
            if len(args) != 1:
                raise AnalysisError("length takes one argument")
            pool = self.pool_of(args[0])
            return ir.DictValueMap(args[0],
                                   tuple(len(s) for s in pool), BIGINT)
        if name == "cardinality":
            if len(args) != 1 or \
                    args[0].dtype.kind is not TypeKind.ARRAY:
                raise AnalysisError("cardinality takes an array")
            pool = self.pool_of(args[0])
            return ir.DictValueMap(args[0],
                                   tuple(len(t) for t in pool), BIGINT)
        if name == "contains":
            if len(args) != 2 or \
                    args[0].dtype.kind is not TypeKind.ARRAY:
                raise AnalysisError("contains(array, constant)")
            pool = self.pool_of(args[0])
            needle = args[1]
            if isinstance(needle, _StringConst):
                v = needle.value
            elif isinstance(needle, ir.Literal):
                v = needle.value
            else:
                raise AnalysisError("contains needle must be a constant")
            from ..types import BOOLEAN as _B
            return ir.DictPredicate(args[0],
                                    tuple(v in t for t in pool), _B)
        if name == "coalesce" and len(args) == 2 and \
                not isinstance(args[0], _StringConst) and \
                args[0].dtype.kind is TypeKind.VARCHAR and \
                isinstance(args[1], _StringConst):
            # varchar coalesce-to-literal: pool transform whose NULL rows
            # take the literal's code. Pools must stay lexicographically
            # sorted (code order == string order is relied on by varchar
            # range compares, ORDER BY, min/max), so an unseen literal is
            # INSERTED at its sorted position and existing codes at or
            # after the insertion point shift up by one.
            import bisect
            col, lit = args[0], args[1].value
            pool = tuple(self.pool_of(col))
            if lit in pool:
                return ir.DerivedDict(col, tuple(range(len(pool))), pool,
                                      col.dtype,
                                      null_code=pool.index(lit))
            ins = bisect.bisect_left(pool, lit)
            new_pool = pool[:ins] + (lit,) + pool[ins:]
            lut = tuple(i if i < ins else i + 1 for i in range(len(pool)))
            return ir.DerivedDict(col, lut, new_pool, col.dtype,
                                  null_code=ins)
        if name == "concat":
            return self.lower_concat(args)
        if name == "replace":
            if len(args) != 3 or not isinstance(args[1], _StringConst) \
                    or not isinstance(args[2], _StringConst):
                raise AnalysisError(
                    "replace(col, 'from', 'to') with literal patterns")
            a, b = args[1].value, args[2].value
            return self.dict_transform(args[0],
                                       lambda s: s.replace(a, b))
        if name == "starts_with":
            if len(args) != 2 or not isinstance(args[1], _StringConst):
                raise AnalysisError(
                    "starts_with(col, 'prefix') with a literal prefix")
            prefix = args[1].value
            return self.dict_lut(args[0],
                                 lambda s: s.startswith(prefix))
        if name in ("strpos", "position"):
            if len(args) != 2 or not isinstance(args[1], _StringConst):
                raise AnalysisError(
                    f"{name}(col, 'needle') with a literal needle")
            needle = args[1].value
            pool = self.pool_of(args[0])
            return ir.DictValueMap(
                args[0], tuple(s.find(needle) + 1 for s in pool), BIGINT)
        if name == "split_part":
            if len(args) != 3 or not isinstance(args[1], _StringConst) \
                    or not isinstance(args[2], ir.Literal):
                raise AnalysisError(
                    "split_part(col, 'delim', n) with literal delim/n")
            delim, idx = args[1].value, int(args[2].value)
            if idx < 1:
                raise AnalysisError("split_part index starts at 1")

            def part(s, d=delim, i=idx):
                fields = s.split(d)
                return fields[i - 1] if i <= len(fields) else ""
            return self.dict_transform(args[0], part)
        if name == "regexp_like":
            if len(args) != 2 or not isinstance(args[1], _StringConst):
                raise AnalysisError(
                    "regexp_like(col, 'pattern') with a literal pattern")
            import re as _re
            pat = _re.compile(args[1].value)
            return self.dict_lut(args[0],
                                 lambda s: pat.search(s) is not None)
        if name == "date_trunc":
            if len(args) != 2 or not isinstance(args[0], _StringConst):
                raise AnalysisError(
                    "date_trunc('unit', x) with a literal unit")
            unit = args[0].value.lower()
            x = args[1]
            kinds = ("year", "quarter", "month", "week", "day")
            if x.dtype.kind is TypeKind.TIMESTAMP:
                kinds = kinds + ("hour", "minute", "second")
            if x.dtype.kind not in (TypeKind.DATE, TypeKind.TIMESTAMP) \
                    or unit not in kinds:
                raise AnalysisError(
                    f"date_trunc unit {unit!r} unsupported for "
                    f"{x.dtype.kind.value}")
            return ir.ExtractField(f"trunc_{unit}", x, x.dtype)
        if name in ("year", "month", "day"):
            if len(args) != 1 or args[0].dtype.kind not in (
                    TypeKind.DATE, TypeKind.TIMESTAMP):
                raise AnalysisError(f"{name} requires a date argument")
            return ir.ExtractField(name, args[0])
        if name in ("hour", "minute", "second"):
            if len(args) != 1 or \
                    args[0].dtype.kind is not TypeKind.TIMESTAMP:
                raise AnalysisError(f"{name} requires a timestamp")
            return ir.ExtractField(name, args[0])

        # -- numeric / conditional ----------------------------------------
        for a in args:
            if isinstance(a, _StringConst):
                raise AnalysisError(
                    f"{name}() does not take string literals")
        if name in ("coalesce", "nullif", "greatest", "least"):
            if name == "nullif" and len(args) != 2:
                raise AnalysisError("nullif takes two arguments")
            if len(args) < 2:
                raise AnalysisError(f"{name} takes at least two arguments")
            out_t = args[0].dtype
            if name != "nullif":
                for a in args[1:]:
                    out_t = common_super_type(out_t, a.dtype)
            return ir.ScalarFunc(name, tuple(args), out_t)
        if name in ("abs", "round", "floor", "ceil", "ceiling"):
            t = args[0].dtype
            digits = ()
            if name == "round" and len(args) == 2:
                if not isinstance(args[1], ir.Literal):
                    raise AnalysisError("round digits must be a literal")
                digits = (int(args[1].value),)
                args = args[:1]
            if name in ("floor", "ceil", "ceiling"):
                out_t = BIGINT if t.kind in (TypeKind.DECIMAL,
                                             TypeKind.BIGINT,
                                             TypeKind.INTEGER) else DOUBLE
                return ir.ScalarFunc("ceil" if name == "ceiling" else name,
                                     tuple(args), out_t)
            return ir.ScalarFunc(name, tuple(args), t, digits)
        if name == "mod":
            if len(args) != 2:
                raise AnalysisError("mod takes two arguments")
            out_t = common_super_type(args[0].dtype, args[1].dtype)
            return ir.ScalarFunc(name, tuple(args), out_t)
        if name in ("sqrt", "power", "pow", "exp", "ln"):
            return ir.ScalarFunc("power" if name == "pow" else name,
                                 tuple(args), DOUBLE)
        raise AnalysisError(f"unsupported function {name}()")

    def dict_transform(self, col: ir.Expr, fn) -> ir.Expr:
        """Apply a host string transform to the pool (DerivedDict)."""
        pool = self.pool_of(col)
        transformed = [fn(s) for s in pool]
        new_pool = tuple(sorted(set(transformed)))
        index = {s: i for i, s in enumerate(new_pool)}
        lut = tuple(index[s] for s in transformed)
        return ir.DerivedDict(col, lut, new_pool, col.dtype
                              if not isinstance(col, _StringConst)
                              else VARCHAR)

    def lower_concat(self, args) -> ir.Expr:
        """col || literal / literal || col (pool transform). col || col
        would explode the pool cross-product — unsupported."""
        cols = [a for a in args
                if not isinstance(a, _StringConst)]
        if len(cols) != 1:
            raise AnalysisError(
                "concat supports one varchar column plus literals")
        col = cols[0]
        if col.dtype.kind is not TypeKind.VARCHAR:
            raise AnalysisError("concat requires varchar arguments")
        prefix = ""
        suffix = ""
        before = True
        for a in args:
            if a is col:
                before = False
            elif isinstance(a, _StringConst):
                if before:
                    prefix += a.value
                else:
                    suffix += a.value
        return self.dict_transform(col,
                                   lambda s: f"{prefix}{s}{suffix}")

    # ---- helpers ----------------------------------------------------------

    def to_bool(self, e: ir.Expr) -> ir.Expr:
        if e.dtype.kind is not TypeKind.BOOLEAN:
            raise AnalysisError("expected boolean expression")
        return e

    def lower_binary(self, node: A.BinaryOp) -> ir.Expr:
        op = node.op
        if op in ("and", "or"):
            return ir.Logical(op, (self.to_bool(self.lower(node.left)),
                                   self.to_bool(self.lower(node.right))))
        if op in ("=", "<>", "<", "<=", ">", ">="):
            left = self.lower(node.left)
            right = self.lower(node.right)
            if isinstance(left, _StringConst) and \
                    right.dtype.kind is TypeKind.VARCHAR:
                return self.dict_compare(right, flip(op), left.value)
            if isinstance(right, _StringConst) and \
                    left.dtype.kind is TypeKind.VARCHAR:
                return self.dict_compare(left, op, right.value)
            if isinstance(left, _StringConst) or \
                    isinstance(right, _StringConst):
                raise AnalysisError("string comparison requires a varchar "
                                    "column side")
            if left.dtype.kind is TypeKind.VARCHAR and \
                    right.dtype.kind is TypeKind.VARCHAR:
                return self.varchar_compare(op, left, right)
            return ir.Compare(op, left, right)
        if op in ("+", "-"):
            # date +/- interval folds at plan time for literal dates,
            # lowers to day arithmetic for day intervals on columns
            if isinstance(node.right, A.IntervalLit):
                left = self.lower(node.left)
                iv = node.right
                if isinstance(left, ir.Literal) and \
                        left.dtype.kind is TypeKind.DATE:
                    return ir.Literal(
                        fold_date_interval(left.value, iv, op == "-"),
                        DATE)
                if left.dtype.kind is TypeKind.DATE and iv.unit == "day":
                    n = -iv.value if (iv.negative != (op == "-")) \
                        else iv.value
                    return ir.arith("+", left, ir.Literal(n, BIGINT))
                raise AnalysisError(
                    "month/year intervals only fold against date literals")
        if op in ("+", "-", "*", "/", "%"):
            left = self.lower(node.left)
            right = self.lower(node.right)
            if op == "%":
                out_t = common_super_type(left.dtype, right.dtype)
                return ir.ScalarFunc("mod", (left, right), out_t)
            return ir.arith(op, left, right)
        if op == "||":
            return self.lower_concat([self.lower(node.left),
                                      self.lower(node.right)])
        raise AnalysisError(f"unsupported operator {op!r}")

    def varchar_compare(self, op: str, left: ir.Expr,
                        right: ir.Expr) -> ir.Expr:
        """varchar-vs-varchar comparison: dictionary codes are only
        comparable within one pool (pools are kept lexicographically
        sorted, so code order == string order). Differing pools: =/<>
        compare through a right->left pool remap (-1 = absent, never
        equal); range comparisons would need a merged ordering — raise."""
        lpool = self.pool_of(left)
        rpool = self.pool_of(right)
        if lpool == rpool:
            return ir.Compare(op, left, right)
        if op not in ("=", "<>"):
            raise AnalysisError(
                "ordered varchar comparison across different dictionaries "
                "is unsupported")
        # both sides become BIGINT codes in the LEFT pool's space
        index = {s: j for j, s in enumerate(lpool)}
        lut = tuple(index.get(s, -1) for s in rpool)
        return ir.Compare(op, ir.Cast(left, BIGINT),
                          ir.DictValueMap(right, lut, BIGINT))

    def lower_case(self, node: A.CaseExpr) -> ir.Expr:
        whens = []
        for cond_ast, val_ast in node.whens:
            if node.operand is not None:
                cond_ast = A.BinaryOp("=", node.operand, cond_ast)
            whens.append((self.to_bool(self.lower(cond_ast)),
                          self.lower(val_ast)))
        default = None if node.default is None else self.lower(node.default)
        # result type: common super type of branch values
        vals = [v for _, v in whens] + ([default] if default else [])
        out_t = vals[0].dtype
        for v in vals[1:]:
            from ..types import common_super_type
            out_t = common_super_type(out_t, v.dtype)
        whens = tuple((c, self.coerce_to(v, out_t)) for c, v in whens)
        default = self.coerce_to(default, out_t) if default else None
        return ir.Case(whens, default, out_t)

    def coerce_to(self, e: ir.Expr, t: DataType) -> ir.Expr:
        if e.dtype == t:
            return e
        return ir.Cast(e, t)

    def coerce_const(self, e: ir.Expr, like: ir.Expr) -> ir.Expr:
        """Coerce literal to the column's type (e.g. decimal rescale)."""
        if isinstance(e, _StringConst):
            raise AnalysisError("cannot compare string to non-varchar")
        return e

    def cast_string_const(self, s: "_StringConst", t: DataType) -> ir.Expr:
        if t.kind is TypeKind.DATE:
            return date_literal(s.value)
        if t.kind is TypeKind.DECIMAL:
            return ir.Literal(
                int(round(float(s.value) * 10 ** t.scale)), t)
        if t.kind in (TypeKind.BIGINT, TypeKind.INTEGER):
            return ir.Literal(int(s.value), t)
        if t.kind is TypeKind.DOUBLE:
            return ir.Literal(float(s.value), t)
        raise AnalysisError(f"cannot cast string literal to {t}")

    # ---- dictionary predicates --------------------------------------------

    def pool_of(self, col: ir.Expr) -> tuple:
        if isinstance(col, ir.DerivedDict):
            return col.pool
        if isinstance(col, ir.ArrayConst):
            return col.pool
        if not isinstance(col, ir.ColumnRef):
            raise AnalysisError("varchar predicate requires a plain column")
        sc = next(c for c in self.scope.columns if c.index == col.index
                  and c.dtype.kind in (TypeKind.VARCHAR, TypeKind.ARRAY))
        if sc.field is None or sc.field.dictionary is None:
            raise AnalysisError(f"column {sc.name} has no dictionary")
        return sc.field.dictionary

    def dict_lut(self, col: ir.Expr, pred) -> ir.Expr:
        pool = self.pool_of(col)
        return ir.DictPredicate(col, tuple(bool(pred(s)) for s in pool))

    def dict_compare(self, col: ir.Expr, op: str, s: str) -> ir.Expr:
        ops = {"=": lambda x: x == s, "<>": lambda x: x != s,
               "<": lambda x: x < s, "<=": lambda x: x <= s,
               ">": lambda x: x > s, ">=": lambda x: x >= s}
        return self.dict_lut(col, ops[op])

    def dict_range(self, col: ir.Expr, low, high) -> ir.Expr:
        lo = low.value if isinstance(low, _StringConst) else None
        hi = high.value if isinstance(high, _StringConst) else None
        if lo is None or hi is None:
            raise AnalysisError("varchar BETWEEN requires string literals")
        return self.dict_lut(col, lambda x: lo <= x <= hi)


@dataclass(frozen=True)
class _StringConst(ir.Expr):
    """Pre-lowering marker for string literals; must be consumed by a
    dictionary predicate before reaching the device."""
    value: str

    @property
    def dtype(self):
        raise AnalysisError(
            f"string literal {self.value!r} used outside a varchar "
            f"comparison context")


def materialize_string(e: ir.Expr) -> ir.Expr:
    """A string literal escaping to a value context (SELECT 'a') becomes a
    VARCHAR Literal with a single-entry pool (code 0); field_for attaches
    the dictionary."""
    if isinstance(e, _StringConst):
        from ..types import VARCHAR
        return ir.Literal(e.value, VARCHAR)
    return e


def flip(op: str) -> str:
    return {"=": "=", "<>": "<>", "<": ">", "<=": ">=",
            ">": "<", ">=": "<="}[op]


def parse_type(name: str) -> DataType:
    name = name.lower()
    if name in ("bigint",):
        return BIGINT
    if name in ("integer", "int", "smallint", "tinyint"):
        from ..types import INTEGER
        return INTEGER
    if name == "double":
        return DOUBLE
    if name == "boolean":
        return BOOLEAN
    if name == "date":
        return DATE
    if name == "timestamp":
        from ..types import TIMESTAMP
        return TIMESTAMP
    m = re.fullmatch(r"decimal\((\d+),(\d+)\)", name)
    if m:
        return decimal(int(m.group(1)), int(m.group(2)))
    if name == "varchar":
        from ..types import VARCHAR
        return VARCHAR
    raise AnalysisError(f"unknown type {name}")
