"""Logical planner: analyzed AST -> logical plan.

Reference: LogicalPlanner/QueryPlanner/RelationPlanner
(sql/planner/LogicalPlanner.java:231) plus the subset of optimizer behavior
that is load-bearing for TPC-H:

- predicate pushdown: WHERE conjuncts applied at the earliest relation where
  all referenced columns exist (PredicatePushDown.java's effect)
- join graph: comma/cross joins + equi-conjuncts assembled into a left-deep
  join tree in FROM order; probe/build orientation chosen so the build side
  is unique on its keys when provable from primary keys
  (DetermineJoinDistributionType.java:51's role, driven by PK metadata
  instead of stats for now)
- aggregate extraction: distinct aggregate calls become AggregateNode slots;
  AVG decomposes into SUM+COUNT with an exact finalizer projection
  (HashAggregationOperator PARTIAL/FINAL + AccumulatorCompiler's job)
- aggregation strategy choice: dense 'direct' when all keys are
  dictionary-coded with a small domain product, else 'sort'
  (GroupByHash.createGroupByHash's Bigint-vs-Flat decision, re-targeted)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import ir
from ..batch import Schema
from ..catalog import Catalog
from ..sql import ast_nodes as A
from ..types import BIGINT, DOUBLE, DataType, TypeKind
from . import logical as L
from .analyzer import (AGG_NAMES, AnalysisError, ExpressionLowerer, Scope,
                       ScopeColumn, ast_children, contains_aggregate,
                       parse_type)

from ..ops.aggregate import MAX_DIRECT_GROUPS  # dense-domain cutoff (64)

DEFAULT_SORT_GROUPS = 1 << 16    # sort-agg output capacity default


@dataclass
class PlannedRelation:
    node: L.PlanNode
    scope: Scope


class Planner:
    def __init__(self, catalog: Catalog, default_catalog: str = "tpch",
                 default_schema: str = "tiny"):
        self.catalog = catalog
        self.default_catalog = default_catalog
        self.default_schema = default_schema

    # ------------------------------------------------------------------
    # relations
    # ------------------------------------------------------------------

    def plan_table(self, ref: A.TableRef) -> PlannedRelation:
        parts = [p.lower() for p in ref.name]
        if len(parts) == 3:
            cat, sch, tbl = parts
        elif len(parts) == 2:
            cat, (sch, tbl) = self.default_catalog, parts
        else:
            cat, sch, tbl = self.default_catalog, self.default_schema, \
                parts[0]
        data = self.catalog.get_table(cat, sch, tbl)
        schema: Schema = data.schema
        qualifier = (ref.alias or tbl).lower()
        output = tuple((f.name, f.dtype) for f in schema)
        node = L.ScanNode(cat, sch, tbl, schema,
                          tuple(range(len(schema.fields))), output)
        cols = [ScopeColumn(qualifier, f.name.lower(), f.dtype, i, f)
                for i, f in enumerate(schema.fields)]
        return PlannedRelation(node, Scope(cols))

    def plan_relation_tree(self, rel: A.Node) -> Tuple[List[PlannedRelation],
                                                       List[A.Node]]:
        """Flatten the FROM tree into base relations + ON conjuncts."""
        relations: List[PlannedRelation] = []
        conjuncts: List[A.Node] = []

        def walk(node: A.Node):
            if isinstance(node, A.TableRef):
                relations.append(self.plan_table(node))
            elif isinstance(node, A.SubqueryRef):
                sub = self.plan_query(node.query)
                alias = node.alias.lower()
                cols = [ScopeColumn(alias, name.lower(), dtype, i, fld)
                        for i, ((name, dtype), fld) in enumerate(
                            zip(sub.node.output, sub_fields(sub)))]
                relations.append(PlannedRelation(sub.node.child
                                                 if isinstance(sub.node,
                                                               L.OutputNode)
                                                 else sub.node,
                                                 Scope(cols)))
            elif isinstance(node, A.Join):
                if node.kind not in ("inner", "cross", "left"):
                    raise AnalysisError(
                        f"{node.kind} join not yet supported")
                if node.kind == "left":
                    # left joins keep tree structure: handled pairwise
                    left = self.combine_relations(*self.subtree(node.left))
                    right = self.combine_relations(*self.subtree(node.right))
                    relations.append(self.plan_left_join(left, right,
                                                         node.condition))
                    return
                walk(node.left)
                walk(node.right)
                if node.condition is not None:
                    split_conjuncts(node.condition, conjuncts)
            else:
                raise AnalysisError(
                    f"unsupported relation {type(node).__name__}")

        walk(rel)
        return relations, conjuncts

    def subtree(self, node: A.Node):
        rels, conj = self.plan_relation_tree(node)
        return rels, conj

    def combine_relations(self, relations, conjuncts) -> PlannedRelation:
        if len(relations) == 1 and not conjuncts:
            return relations[0]
        return self.build_join_tree(relations, list(conjuncts))

    # ------------------------------------------------------------------
    # join tree assembly
    # ------------------------------------------------------------------

    def build_join_tree(self, relations: List[PlannedRelation],
                        conjuncts: List[A.Node]) -> PlannedRelation:
        """Left-deep join in FROM order; equi-conjuncts become join keys,
        single-relation conjuncts push down, leftovers become filters."""
        acc = relations[0]
        acc = self.apply_local_filters(acc, conjuncts)
        for nxt in relations[1:]:
            nxt = self.apply_local_filters(nxt, conjuncts)
            acc = self.join_pair(acc, nxt, conjuncts, kind="inner")
            acc = self.apply_local_filters(acc, conjuncts)
        return acc

    def apply_local_filters(self, rel: PlannedRelation,
                            conjuncts: List[A.Node]) -> PlannedRelation:
        """Push down any pending conjunct fully resolvable in this scope."""
        applied = []
        preds = []
        for c in conjuncts:
            lowerer = ExpressionLowerer(rel.scope)
            try:
                preds.append(lowerer.to_bool(lowerer.lower(c)))
                applied.append(c)
            except AnalysisError:
                continue
        for c in applied:
            conjuncts.remove(c)
        if not preds:
            return rel
        pred = preds[0] if len(preds) == 1 else ir.Logical(
            "and", tuple(preds))
        node = L.FilterNode(rel.node, pred, rel.node.output)
        return PlannedRelation(node, rel.scope)

    def join_pair(self, left: PlannedRelation, right: PlannedRelation,
                  conjuncts: List[A.Node], kind: str) -> PlannedRelation:
        """Extract equi-conjuncts linking left & right; orient probe/build."""
        left_keys: List[int] = []
        right_keys: List[int] = []
        used: List[A.Node] = []
        for c in conjuncts:
            eq = as_equi(c)
            if eq is None:
                continue
            a, b = eq
            la = left.scope.try_resolve(a)
            rb = right.scope.try_resolve(b)
            if la is not None and rb is not None:
                left_keys.append(la.index)
                right_keys.append(rb.index)
                used.append(c)
                continue
            lb = left.scope.try_resolve(b)
            ra = right.scope.try_resolve(a)
            if lb is not None and ra is not None:
                left_keys.append(lb.index)
                right_keys.append(ra.index)
                used.append(c)
        for c in used:
            conjuncts.remove(c)
        if not left_keys:
            raise AnalysisError(
                "cross join without equi-condition not yet supported")

        # orientation: build side should be unique on its keys if provable;
        # LEFT joins pin the preserved side as probe (no freedom)
        right_unique = self.is_unique(right, right_keys)
        left_unique = self.is_unique(left, left_keys)
        if kind == "left" or right_unique or not left_unique:
            probe, build = left, right
            probe_keys, build_keys = left_keys, right_keys
            build_unique = right_unique
        else:
            probe, build = right, left
            probe_keys, build_keys = right_keys, left_keys
            build_unique = left_unique

        output = tuple(probe.node.output) + tuple(build.node.output)
        node = L.JoinNode(kind, probe.node, build.node,
                          tuple(probe_keys), tuple(build_keys), None,
                          build_unique, output)
        n_left = len(probe.node.output)
        cols = list(probe.scope.columns) + [
            ScopeColumn(c.qualifier, c.name, c.dtype, c.index + n_left,
                        c.field) for c in build.scope.columns]
        return PlannedRelation(node, Scope(cols))

    def plan_left_join(self, left: PlannedRelation, right: PlannedRelation,
                       condition: Optional[A.Node]) -> PlannedRelation:
        conjuncts: List[A.Node] = []
        if condition is not None:
            split_conjuncts(condition, conjuncts)
        rel = self.join_pair(left, right, conjuncts, kind="left")
        if conjuncts:
            raise AnalysisError("non-equi LEFT JOIN condition unsupported")
        return rel

    def is_unique(self, rel: PlannedRelation, key_indices: List[int]) -> bool:
        return self.node_unique_on(rel.node, frozenset(key_indices))

    def node_unique_on(self, node: L.PlanNode, keys: frozenset) -> bool:
        """True if `node`'s output is provably unique on the given column
        positions. The planner's stand-in for Trino's stats-derived
        distinct-count reasoning (DetermineJoinDistributionType.java:51):
        primary keys at scans, propagated through filters, unique-build
        joins (probe multiplicity preserved) and aggregations (output is
        unique on its group keys)."""
        if isinstance(node, (L.FilterNode, L.SortNode, L.LimitNode)):
            return self.node_unique_on(node.child, keys)
        if isinstance(node, L.ProjectNode):
            mapped = set()
            for i in keys:
                e = node.exprs[i]
                if not isinstance(e, ir.ColumnRef):
                    return False
                mapped.add(e.index)
            return self.node_unique_on(node.child, frozenset(mapped))
        if isinstance(node, L.ScanNode):
            data = self.catalog.get_table(node.catalog, node.schema_name,
                                          node.table)
            if not data.primary_key:
                return False
            key_names = {node.output[i][0].lower() for i in keys}
            return set(k.lower() for k in data.primary_key) <= key_names
        if isinstance(node, L.JoinNode):
            if node.kind in ("inner", "left") and node.build_unique:
                n_probe = len(node.left.output)
                if all(i < n_probe for i in keys):
                    return self.node_unique_on(node.left, keys)
            if node.kind in ("semi", "anti"):
                return self.node_unique_on(node.left, keys)
            return False
        if isinstance(node, L.AggregateNode):
            n_group = len(node.group_keys)
            return set(range(n_group)) <= keys
        return False

    # ------------------------------------------------------------------
    # query planning
    # ------------------------------------------------------------------

    def plan_query(self, q: A.Query) -> PlannedRelation:
        if q.relation is None:
            raise AnalysisError("SELECT without FROM not yet supported")
        relations, on_conjuncts = self.plan_relation_tree(q.relation)

        conjuncts: List[A.Node] = list(on_conjuncts)
        if q.where is not None:
            split_conjuncts(q.where, conjuncts)

        if len(relations) == 1:
            rel = self.apply_local_filters(relations[0], conjuncts)
        else:
            rel = self.build_join_tree(relations, conjuncts)
        # residual multi-relation predicates (e.g. q19's OR-of-blocks)
        # become filters over the joined scope
        rel = self.apply_local_filters(rel, conjuncts)
        if conjuncts:
            raise AnalysisError(
                f"unplaced predicate(s): {conjuncts}")

        has_agg = any(contains_aggregate(i.expr) for i in q.select
                      if i.expr is not None) or q.group_by or \
            (q.having is not None)

        if has_agg:
            rel, select_scope_exprs, names = self.plan_aggregation(q, rel)
        else:
            rel, select_scope_exprs, names = self.plan_plain_select(q, rel)

        # DISTINCT via group-by-all-columns (Trino rewrites the same way)
        if q.distinct:
            node = rel.node
            ncols = len(node.output)
            rel = PlannedRelation(
                L.AggregateNode(node, tuple(range(ncols)), (), "sort", (),
                                DEFAULT_SORT_GROUPS, node.output),
                rel.scope)

        # ORDER BY over the select output scope (+ alias resolution)
        if q.order_by:
            keys = []
            for item in q.order_by:
                idx = self.resolve_order_expr(item.expr, q, rel, names)
                nulls_first = item.nulls_first
                if nulls_first is None:
                    nulls_first = not item.ascending   # Trino default
                keys.append(L.SortKey(idx, item.ascending, nulls_first))
            rel = PlannedRelation(
                L.SortNode(rel.node, tuple(keys), q.limit, rel.node.output),
                rel.scope)
        elif q.limit is not None:
            rel = PlannedRelation(
                L.LimitNode(rel.node, q.limit, rel.node.output), rel.scope)

        out = L.OutputNode(rel.node, tuple(names), rel.node.output)
        return PlannedRelation(out, rel.scope)

    # ---- plain select -----------------------------------------------------

    def expand_star(self, q: A.Query, scope: Scope):
        items = []
        for item in q.select:
            if item.expr is None:
                qual = None
                if item.star_qualifier:
                    qual = item.star_qualifier[-1].lower()
                for c in scope.columns:
                    if qual is None or c.qualifier == qual:
                        items.append((A.Identifier((c.qualifier, c.name)),
                                      c.name))
            else:
                name = item.alias or default_name(item.expr)
                items.append((item.expr, name.lower()))
        return items

    def plan_plain_select(self, q: A.Query, rel: PlannedRelation):
        items = self.expand_star(q, rel.scope)
        lowerer = ExpressionLowerer(rel.scope)
        exprs = []
        names = []
        out_cols = []
        new_scope = []
        for i, (ast, name) in enumerate(items):
            e = lowerer.lower(ast)
            exprs.append(e)
            names.append(name)
            out_cols.append((name, e.dtype))
            fld = self.field_for(e, rel.scope)
            new_scope.append(ScopeColumn(None, name, e.dtype, i, fld))
        node = L.ProjectNode(rel.node, tuple(exprs), tuple(out_cols))
        return PlannedRelation(node, Scope(new_scope)), exprs, names

    def field_for(self, e: ir.Expr, scope: Scope):
        """Propagate dictionary fields through bare column projections."""
        if isinstance(e, ir.ColumnRef) and \
                e.dtype.kind is TypeKind.VARCHAR:
            for c in scope.columns:
                if c.index == e.index and c.dtype.kind is TypeKind.VARCHAR:
                    return c.field
        return None

    # ---- aggregation ------------------------------------------------------

    def plan_aggregation(self, q: A.Query, rel: PlannedRelation):
        scope = rel.scope
        lowerer = ExpressionLowerer(scope)

        group_asts = list(q.group_by)
        group_irs = [lowerer.lower(resolve_ordinal(g, q)) for g in group_asts]

        # collect distinct aggregate calls across select/having/order
        agg_calls: List[A.FunctionCall] = []

        def collect(node: A.Node):
            if isinstance(node, A.FunctionCall) and node.name in AGG_NAMES:
                if node not in agg_calls:
                    agg_calls.append(node)
                return
            for ch in ast_children(node):
                collect(ch)

        for item in q.select:
            if item.expr is not None:
                collect(item.expr)
        if q.having is not None:
            collect(q.having)
        for o in q.order_by:
            collect(o.expr)

        # pre-projection: group keys then agg args
        pre_exprs: List[ir.Expr] = list(group_irs)
        pre_cols: List[Tuple[str, DataType]] = [
            (f"gk{i}", e.dtype) for i, e in enumerate(group_irs)]
        agg_specs: List[L.AggSpecNode] = []
        # map from agg call -> (post-agg expression builder)
        call_slots: Dict[A.FunctionCall, Tuple[str, int, int]] = {}

        def add_arg(e: ir.Expr) -> int:
            pre_exprs.append(e)
            pre_cols.append((f"a{len(pre_exprs)}", e.dtype))
            return len(pre_exprs) - 1

        n_keys = len(group_irs)
        for call in agg_calls:
            if call.distinct:
                raise AnalysisError("DISTINCT aggregates not yet supported")
            if call.is_star or (call.name == "count" and not call.args):
                agg_specs.append(L.AggSpecNode("count_star", None,
                                               "count", BIGINT))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
                continue
            if len(call.args) != 1:
                raise AnalysisError(f"{call.name} takes one argument")
            arg = lowerer.lower(call.args[0])
            slot = add_arg(arg)
            t = arg.dtype
            if call.name == "count":
                agg_specs.append(L.AggSpecNode("count", ir.ColumnRef(
                    slot, t), "count", BIGINT))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
            elif call.name in ("min", "max"):
                agg_specs.append(L.AggSpecNode(call.name, ir.ColumnRef(
                    slot, t), call.name, t))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
            elif call.name == "sum":
                out_t = sum_type(t)
                agg_specs.append(L.AggSpecNode("sum", ir.ColumnRef(slot, t),
                                               "sum", out_t))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
            elif call.name == "avg":
                out_t = t if t.kind is TypeKind.DECIMAL else DOUBLE
                agg_specs.append(L.AggSpecNode("sum", ir.ColumnRef(slot, t),
                                               "avg_sum", sum_type(t)))
                agg_specs.append(L.AggSpecNode("count", ir.ColumnRef(
                    slot, t), "avg_cnt", BIGINT))
                call_slots[call] = ("avg", len(agg_specs) - 2,
                                    len(agg_specs) - 1)

        pre_node = L.ProjectNode(rel.node, tuple(pre_exprs),
                                 tuple(pre_cols))

        # aggregation strategy
        strategy, domains, capacity = self.agg_strategy(
            group_irs, scope, pre_node)
        agg_out = tuple(
            [(f"gk{i}", e.dtype) for i, e in enumerate(group_irs)] +
            [(s.out_name, s.out_dtype) for s in agg_specs])
        agg_node = L.AggregateNode(
            pre_node, tuple(range(n_keys)), tuple(agg_specs),
            strategy, domains, capacity, agg_out)

        # post-projection scope: group keys (referencing original key ASTs)
        # then aggregate slots
        post_scope_cols = []
        for i, (g_ast, g_ir) in enumerate(zip(group_asts, group_irs)):
            fld = self.field_for(g_ir, scope)
            post_scope_cols.append(ScopeColumn(None, f"gk{i}", g_ir.dtype,
                                               i, fld))
        post_scope = Scope(post_scope_cols)

        def rewrite(node: A.Node) -> ir.Expr:
            """Lower a select/having/order expression over the agg output."""
            # group-by expression match (syntactic, like Trino)
            for i, g_ast in enumerate(group_asts):
                if ast_equal(node, g_ast, q):
                    c = post_scope.columns[i]
                    return ir.ColumnRef(c.index, c.dtype, c.name)
            if isinstance(node, A.FunctionCall) and node.name in AGG_NAMES:
                kind, s1, s2 = call_slots[node]
                if kind == "plain":
                    spec = agg_specs[s1]
                    return ir.ColumnRef(n_keys + s1, spec.out_dtype)
                sum_ref = ir.ColumnRef(n_keys + s1, agg_specs[s1].out_dtype)
                cnt_ref = ir.ColumnRef(n_keys + s2, BIGINT)
                arg_t = agg_specs[s1].arg.dtype
                if arg_t.kind is TypeKind.DECIMAL:
                    return ir.DecimalAvg(sum_ref, cnt_ref, arg_t)
                return ir.arith("/", ir.Cast(sum_ref, DOUBLE),
                                ir.Cast(cnt_ref, DOUBLE))
            if isinstance(node, A.Identifier):
                # must be a group key (matched above) — else error
                raise AnalysisError(
                    f"column {'.'.join(node.parts)} must appear in GROUP BY")
            if isinstance(node, A.BinaryOp):
                l, r = rewrite(node.left), rewrite(node.right)
                if node.op in ("and", "or"):
                    return ir.Logical(node.op, (l, r))
                if node.op in ("=", "<>", "<", "<=", ">", ">="):
                    return ir.Compare(node.op, l, r)
                return ir.arith(node.op, l, r)
            if isinstance(node, A.UnaryOp):
                if node.op == "not":
                    return ir.Not(rewrite(node.arg))
                return ir.Negate(rewrite(node.arg),
                                 rewrite(node.arg).dtype)
            if isinstance(node, (A.NumberLit, A.StringLit, A.BoolLit,
                                 A.NullLit, A.DateLit)):
                return ExpressionLowerer(post_scope).lower(node)
            if isinstance(node, A.CastExpr):
                return ir.Cast(rewrite(node.arg),
                               parse_type(node.type_name))
            raise AnalysisError(
                f"unsupported post-aggregation expression "
                f"{type(node).__name__}")

        items = []
        for item in q.select:
            if item.expr is None:
                raise AnalysisError("* not allowed with GROUP BY")
            name = (item.alias or default_name(item.expr)).lower()
            items.append((item.expr, name))

        post_exprs = []
        names = []
        out_cols = []
        final_scope = []
        for i, (ast, name) in enumerate(items):
            e = rewrite(ast)
            post_exprs.append(e)
            names.append(name)
            out_cols.append((name, e.dtype))
            fld = None
            if isinstance(e, ir.ColumnRef) and e.index < n_keys:
                fld = post_scope.columns[e.index].field
            final_scope.append(ScopeColumn(None, name, e.dtype, i, fld))

        current: L.PlanNode = agg_node
        if q.having is not None:
            pred = rewrite(q.having)
            current = L.FilterNode(current, pred, current.output)
        post_node = L.ProjectNode(current, tuple(post_exprs),
                                  tuple(out_cols))
        return (PlannedRelation(post_node, Scope(final_scope)),
                post_exprs, names)

    def agg_strategy(self, group_irs, scope: Scope, pre_node):
        if not group_irs:
            return "global", (), 0
        domains = []
        for e in group_irs:
            d = self.domain_of(e, scope)
            if d is None:
                domains = None
                break
            domains.append(d)
        if domains is not None:
            prod = math.prod(domains)
            if prod <= MAX_DIRECT_GROUPS:
                return "direct", tuple(domains), prod
        return "sort", (), DEFAULT_SORT_GROUPS

    def domain_of(self, e: ir.Expr, scope: Scope) -> Optional[int]:
        if isinstance(e, ir.ColumnRef):
            if e.dtype.kind is TypeKind.VARCHAR:
                for c in scope.columns:
                    if c.index == e.index and c.field is not None and \
                            c.field.dictionary is not None:
                        return len(c.field.dictionary)
            if e.dtype.kind is TypeKind.BOOLEAN:
                return 2
        return None

    def resolve_order_expr(self, ast: A.Node, q: A.Query,
                           rel: PlannedRelation, names: List[str]) -> int:
        # ordinal
        if isinstance(ast, A.NumberLit) and "." not in ast.text:
            i = int(ast.text) - 1
            if not (0 <= i < len(names)):
                raise AnalysisError(f"ORDER BY position {i+1} out of range")
            return i
        # alias or column name in output
        if isinstance(ast, A.Identifier) and len(ast.parts) == 1:
            nm = ast.parts[0].lower()
            if nm in names:
                return names.index(nm)
        # expression identical to some select item
        for i, item in enumerate(q.select):
            if item.expr is not None and ast_equal(ast, item.expr, q):
                return i
        raise AnalysisError(
            "ORDER BY expressions must reference select outputs for now")


# --------------------------------------------------------------------------
# small helpers
# --------------------------------------------------------------------------

def split_conjuncts(node: A.Node, out: List[A.Node]) -> None:
    if isinstance(node, A.BinaryOp) and node.op == "and":
        split_conjuncts(node.left, out)
        split_conjuncts(node.right, out)
    else:
        out.append(node)


def as_equi(node: A.Node):
    if isinstance(node, A.BinaryOp) and node.op == "=" and \
            isinstance(node.left, A.Identifier) and \
            isinstance(node.right, A.Identifier):
        return node.left.parts, node.right.parts
    return None


def ast_equal(a: A.Node, b: A.Node, q: A.Query) -> bool:
    """Syntactic equality; also matches a bare identifier against a select
    alias (SQL: GROUP BY can reference aliases in some dialects — Trino
    allows ordinals and output names; we match structurally)."""
    return a == b


def resolve_ordinal(g: A.Node, q: A.Query) -> A.Node:
    if isinstance(g, A.NumberLit) and "." not in g.text:
        i = int(g.text) - 1
        if 0 <= i < len(q.select) and q.select[i].expr is not None:
            return q.select[i].expr
    return g


def default_name(expr: A.Node) -> str:
    if isinstance(expr, A.Identifier):
        return expr.parts[-1]
    if isinstance(expr, A.FunctionCall):
        return expr.name
    return "_col"


def sum_type(t: DataType) -> DataType:
    if t.kind is TypeKind.DECIMAL:
        from ..types import decimal as mk
        return mk(18, t.scale)     # widest short decimal (int64 accumulator)
    if t.kind is TypeKind.DOUBLE:
        return DOUBLE
    return BIGINT


def sub_fields(sub: "PlannedRelation"):
    """Fields (with dictionaries) for a subquery's output columns."""
    return [c.field for c in sub.scope.columns]
