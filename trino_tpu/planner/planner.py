"""Logical planner: analyzed AST -> logical plan.

Reference: LogicalPlanner/QueryPlanner/RelationPlanner
(sql/planner/LogicalPlanner.java:231) plus the subset of optimizer behavior
that is load-bearing for TPC-H:

- predicate pushdown: WHERE conjuncts applied at the earliest relation where
  all referenced columns exist (PredicatePushDown.java's effect)
- join graph: comma/cross joins + equi-conjuncts assembled into a left-deep
  join tree in FROM order; probe/build orientation chosen so the build side
  is unique on its keys when provable from primary keys
  (DetermineJoinDistributionType.java:51's role, driven by PK metadata
  instead of stats for now)
- aggregate extraction: distinct aggregate calls become AggregateNode slots;
  AVG decomposes into SUM+COUNT with an exact finalizer projection
  (HashAggregationOperator PARTIAL/FINAL + AccumulatorCompiler's job)
- aggregation strategy choice: dense 'direct' when all keys are
  dictionary-coded with a small domain product, else 'sort'
  (GroupByHash.createGroupByHash's Bigint-vs-Flat decision, re-targeted)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import ir
from ..batch import Field, Schema
from ..catalog import Catalog
from ..sql import ast_nodes as A
from ..types import (BIGINT, BOOLEAN, DOUBLE, VARCHAR, DataType, TypeKind,
                     common_super_type)
from . import logical as L
from .analyzer import (AGG_NAMES, VARIANCE_AGGS, AnalysisError,
                       ExpressionLowerer, Scope, ScopeColumn, ast_children,
                       contains_aggregate, date_literal, flip,
                       materialize_string, number_literal, parse_type)

from ..ops.aggregate import MAX_DIRECT_GROUPS  # dense-domain cutoff (64)

DEFAULT_SORT_GROUPS = 1 << 16    # sort-agg output capacity default
# HyperLogLog precision for approx_distinct: 2^12 registers gives ~1.6%
# standard error (inside the reference's 2.3% default,
# ApproximateCountDistinctAggregation.java's maxStandardError)
HLL_P = 12


def _scale_of(dtype) -> int:
    return dtype.scale if dtype is not None and \
        dtype.kind is TypeKind.DECIMAL else 0


def _remap_lut(lpool: tuple, rpool: tuple) -> tuple:
    """Per-code LUT translating rpool codes into lpool codes; -1 = the
    string is absent from lpool (matches no valid code)."""
    index = {s: j for j, s in enumerate(lpool)}
    return tuple(index.get(s, -1) for s in rpool)


@dataclass
class PlannedRelation:
    node: L.PlanNode
    scope: Scope


class Planner:
    def __init__(self, catalog: Catalog, default_catalog: str = "tpch",
                 default_schema: str = "tiny", properties=None):
        self.catalog = catalog
        self.default_catalog = default_catalog
        self.default_schema = default_schema
        self.properties = properties or {}
        self.ctes: Dict[str, A.Query] = {}   # WITH-bound names, lexically scoped
        # (from_node, from_scope, window_slots) of the latest plain select —
        # lets ORDER BY lower hidden sort expressions over the FROM scope
        self._plain_from: Optional[tuple] = None

    # ------------------------------------------------------------------
    # relations
    # ------------------------------------------------------------------

    def plan_table(self, ref: A.TableRef) -> PlannedRelation:
        parts = [p.lower() for p in ref.name]
        if len(parts) == 1 and parts[0] in self.ctes:
            # a CTE body must not see its own binding (non-recursive WITH)
            saved = self.ctes
            self.ctes = {k: v for k, v in self.ctes.items()
                         if k != parts[0]}
            try:
                sub = self.plan_query(saved[parts[0]])
            finally:
                self.ctes = saved
            return self.wrap_subplan(sub, (ref.alias or parts[0]).lower())
        if len(parts) == 3:
            cat, sch, tbl = parts
        elif len(parts) == 2:
            cat, (sch, tbl) = self.default_catalog, parts
        else:
            cat, sch, tbl = self.default_catalog, self.default_schema, \
                parts[0]
        data = self.catalog.get_table(cat, sch, tbl)
        schema: Schema = data.schema
        qualifier = (ref.alias or tbl).lower()
        output = tuple((f.name, f.dtype) for f in schema)
        node = L.ScanNode(cat, sch, tbl, schema,
                          tuple(range(len(schema.fields))), output)
        cols = [ScopeColumn(qualifier, f.name.lower(), f.dtype, i, f)
                for i, f in enumerate(schema.fields)]
        return PlannedRelation(node, Scope(cols))

    def wrap_subplan(self, sub: "PlannedRelation",
                     alias: str) -> PlannedRelation:
        """Embed a planned subquery/CTE as a relation under `alias`."""
        node = sub.node.child if isinstance(sub.node, L.OutputNode) \
            else sub.node
        cols = [ScopeColumn(alias, name.lower(), dtype, i, fld)
                for i, ((name, dtype), fld) in enumerate(
                    zip(node.output, sub_fields(sub)))]
        return PlannedRelation(node, Scope(cols))

    # ------------------------------------------------------------------
    # VALUES and set operations
    # ------------------------------------------------------------------

    def eval_const_ast(self, node: A.Node) -> ir.Literal:
        """Evaluate a constant VALUES cell at plan time (tree/Values.java
        rows are bound during analysis in the reference too)."""
        if isinstance(node, A.NumberLit):
            return number_literal(node.text)
        if isinstance(node, A.StringLit):
            return ir.Literal(node.value, VARCHAR)
        if isinstance(node, A.BoolLit):
            return ir.Literal(node.value, BOOLEAN)
        if isinstance(node, A.NullLit):
            return ir.Literal(None, None)
        if isinstance(node, A.DateLit):
            return date_literal(node.value)
        if isinstance(node, A.TimestampLit):
            from .analyzer import timestamp_literal
            return timestamp_literal(node.value)
        if isinstance(node, A.UnaryOp) and node.op == "-":
            lit = self.eval_const_ast(node.arg)
            if lit.value is None:
                return lit
            return ir.Literal(-lit.value, lit.dtype)
        if isinstance(node, A.BinaryOp) and node.op in "+-*":
            l = self.eval_const_ast(node.left)
            r = self.eval_const_ast(node.right)
            if l.dtype is not None and r.dtype is not None and \
                    l.dtype.kind is TypeKind.BIGINT and \
                    r.dtype.kind is TypeKind.BIGINT:
                v = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
                     "*": lambda a, b: a * b}[node.op](l.value, r.value)
                return ir.Literal(v, BIGINT)
        if isinstance(node, A.CastExpr):
            lit = self.eval_const_ast(node.arg)
            dst = parse_type(node.type_name)
            return ir.Literal(_convert_const(lit.value, lit.dtype, dst), dst)
        raise AnalysisError(
            f"unsupported constant expression in VALUES: "
            f"{type(node).__name__}")

    def plan_values_ref(self, ref: A.ValuesRef) -> PlannedRelation:
        rows = ref.values.rows
        arity = len(rows[0])
        for r in rows:
            if len(r) != arity:
                raise AnalysisError("VALUES rows have mixed column counts")
        cells = [[self.eval_const_ast(c) for c in r] for r in rows]
        names = [n.lower() for n in ref.column_names] \
            if ref.column_names else [f"_col{j}" for j in range(arity)]
        if ref.column_names and len(ref.column_names) != arity:
            raise AnalysisError("VALUES column alias count mismatch")

        arrays, valids, fields, output, cols = [], [], [], [], []
        alias = ref.alias.lower()
        for j in range(arity):
            col_lits = [row[j] for row in cells]
            dtype = None
            for lit in col_lits:
                if lit.dtype is None:
                    continue
                dtype = lit.dtype if dtype is None else \
                    common_super_type(dtype, lit.dtype)
            if dtype is None:
                dtype = BIGINT      # all-NULL column
            valid = np.array([lit.dtype is not None and lit.value is not None
                              for lit in col_lits], dtype=np.bool_)
            dictionary = None
            if dtype.kind is TypeKind.VARCHAR:
                # pool must be SORTED (code order == string order is the
                # engine-wide invariant sorts and min/max rely on)
                strings = [lit.value if lit.value is not None else ""
                           for lit in col_lits]
                pool = sorted(set(strings))
                index = {s: k for k, s in enumerate(pool)}
                data = np.asarray([index[s] for s in strings],
                                  dtype=dtype.np_dtype)
                dictionary = tuple(pool)
            else:
                data = np.asarray(
                    [_convert_const(lit.value, lit.dtype, dtype) or 0
                     for lit in col_lits], dtype=dtype.np_dtype)
            fld = Field(names[j], dtype, dictionary)
            arrays.append(data)
            valids.append(valid)
            fields.append(fld)
            output.append((names[j], dtype))
            cols.append(ScopeColumn(alias, names[j], dtype, j, fld))
        node = L.ValuesNode(tuple(arrays), tuple(valids), len(rows),
                            tuple(fields), tuple(output))
        return PlannedRelation(node, Scope(cols))

    def plan_values_statement(self, v: A.Values) -> PlannedRelation:
        rel = self.plan_values_ref(A.ValuesRef(v, "values"))
        names = tuple(n for n, _ in rel.node.output)
        out = L.OutputNode(rel.node, names, rel.node.output)
        return PlannedRelation(out, rel.scope)

    def plan_body(self, node: A.Node) -> PlannedRelation:
        """Plan a set-op operand to a relation (no OutputNode root)."""
        if isinstance(node, A.Values):
            return self.plan_values_ref(A.ValuesRef(node, "values"))
        sub = self.plan_query(node)
        return self.wrap_subplan(sub, "$setop")

    def plan_setop(self, q: A.SetOp) -> PlannedRelation:
        left = self.plan_body(q.left)
        right = self.plan_body(q.right)
        if len(left.node.output) != len(right.node.output):
            raise AnalysisError(
                f"set operation column count mismatch: "
                f"{len(left.node.output)} vs {len(right.node.output)}")
        left, right, out_fields, lremaps, rremaps = \
            self.align_setop(left, right)
        names = [c.name for c in left.scope.columns]
        output = tuple((nm, f.dtype) for nm, f in zip(names, out_fields))
        op = q.op + ("_all" if q.all_rows else "")
        node = L.SetOpNode(op, left.node, right.node, tuple(lremaps),
                           tuple(rremaps), output)
        cols = [ScopeColumn(None, nm, f.dtype, i, f)
                for i, (nm, f) in enumerate(zip(names, out_fields))]
        rel = PlannedRelation(node, Scope(cols))

        if q.order_by:
            keys = []
            for item in q.order_by:
                idx = self.resolve_setop_order(item.expr, names)
                nulls_first = item.nulls_first
                if nulls_first is None:
                    nulls_first = not item.ascending
                keys.append(L.SortKey(idx, item.ascending, nulls_first))
            rel = PlannedRelation(
                L.SortNode(rel.node, tuple(keys), q.limit, rel.node.output),
                rel.scope)
        elif q.limit is not None:
            rel = PlannedRelation(
                L.LimitNode(rel.node, q.limit, rel.node.output), rel.scope)
        out = L.OutputNode(rel.node, tuple(names), rel.node.output)
        return PlannedRelation(out, rel.scope)

    def resolve_setop_order(self, ast: A.Node, names: List[str]) -> int:
        if isinstance(ast, A.NumberLit) and "." not in ast.text:
            k = int(ast.text)
            if not (1 <= k <= len(names)):
                raise AnalysisError(f"ORDER BY ordinal {k} out of range")
            return k - 1
        if isinstance(ast, A.Identifier) and len(ast.parts) == 1:
            nm = ast.parts[0].lower()
            if nm in names:
                return names.index(nm)
        raise AnalysisError(
            "ORDER BY over a set operation must reference an output "
            "column name or ordinal")

    def align_setop(self, left: PlannedRelation, right: PlannedRelation):
        """Coerce both sides to common column types; merge VARCHAR
        dictionaries (right codes remap through the merged pool)."""
        lcols, rcols = left.scope.columns, right.scope.columns
        lcasts, rcasts, out_fields, lremaps, rremaps = [], [], [], [], []
        for i, (lc, rc) in enumerate(zip(lcols, rcols)):
            lt, rt = lc.dtype, rc.dtype
            if lt.kind is TypeKind.VARCHAR or rt.kind is TypeKind.VARCHAR:
                if lt.kind is not rt.kind:
                    raise AnalysisError(
                        "set operation mixes VARCHAR and non-VARCHAR")
                ld = lc.field.dictionary if lc.field else ()
                rd = rc.field.dictionary if rc.field else ()
                if ld == rd:
                    lremaps.append(None)
                    rremaps.append(None)
                    out_fields.append(Field(lc.name, lt, ld))
                else:
                    # merged pool is SORTED: the engine-wide invariant that
                    # dictionary code order == string order (ORDER BY and
                    # min/max on varchar sort codes directly) must survive
                    # the merge, so both sides get a remap LUT
                    merged = sorted(set(ld) | set(rd))
                    index = {s: k for k, s in enumerate(merged)}
                    lr = tuple(index[s] for s in ld)
                    rr = tuple(index[s] for s in rd)
                    lremaps.append(
                        None if lr == tuple(range(len(ld))) else lr)
                    rremaps.append(
                        None if rr == tuple(range(len(rd))) else rr)
                    out_fields.append(Field(lc.name, lt, tuple(merged)))
                lcasts.append(None)
                rcasts.append(None)
                continue
            try:
                target = common_super_type(lt, rt)
            except Exception:
                raise AnalysisError(
                    f"set operation type mismatch on column {i}: "
                    f"{lt} vs {rt}")
            lcasts.append(None if lt == target else target)
            rcasts.append(None if rt == target else target)
            out_fields.append(Field(lc.name, target, None))
            lremaps.append(None)
            rremaps.append(None)
        left = _cast_relation(left, lcasts)
        right = _cast_relation(right, rcasts)
        return left, right, out_fields, lremaps, rremaps

    def plan_relation_tree(self, rel: A.Node, unnests=None) \
            -> Tuple[List[PlannedRelation], List[A.Node]]:
        """Flatten the FROM tree into base relations + ON conjuncts.
        UNNEST items collect into `unnests` (lateral: they expand the
        combined preceding relations); passing None rejects them."""
        relations: List[PlannedRelation] = []
        conjuncts: List[A.Node] = []

        def walk(node: A.Node):
            if isinstance(node, A.UnnestRef):
                if unnests is None:
                    raise AnalysisError(
                        "UNNEST not supported in this position")
                unnests.append(node)
            elif isinstance(node, A.TableRef):
                relations.append(self.plan_table(node))
            elif isinstance(node, A.ValuesRef):
                relations.append(self.plan_values_ref(node))
            elif isinstance(node, A.SubqueryRef):
                sub = self.plan_query(node.query)
                relations.append(self.wrap_subplan(sub, node.alias.lower()))
            elif isinstance(node, A.Join):
                if node.kind not in ("inner", "cross", "left", "right",
                                     "full"):
                    raise AnalysisError(
                        f"{node.kind} join not yet supported")
                if node.kind in ("left", "right", "full"):
                    # outer joins keep tree structure: handled pairwise
                    left = self.combine_relations(*self.subtree(node.left))
                    right = self.combine_relations(*self.subtree(node.right))
                    planner = {"left": self.plan_left_join,
                               "right": self.plan_right_join,
                               "full": self.plan_full_join}[node.kind]
                    relations.append(planner(left, right, node.condition))
                    return
                walk(node.left)
                walk(node.right)
                if node.condition is not None:
                    split_conjuncts(node.condition, conjuncts)
            else:
                raise AnalysisError(
                    f"unsupported relation {type(node).__name__}")

        walk(rel)
        return relations, conjuncts

    def subtree(self, node: A.Node):
        rels, conj = self.plan_relation_tree(node)
        return rels, conj

    def combine_relations(self, relations, conjuncts) -> PlannedRelation:
        if len(relations) == 1 and not conjuncts:
            return relations[0]
        return self.build_join_tree(relations, list(conjuncts))

    # ------------------------------------------------------------------
    # join tree assembly
    # ------------------------------------------------------------------

    def build_join_tree(self, relations: List[PlannedRelation],
                        conjuncts: List[A.Node]) -> PlannedRelation:
        """Left-deep join tree; equi-conjuncts become join keys,
        single-relation conjuncts push down, leftovers become filters.

        Order: cost-driven greedy — start from the largest relation (it
        stays the probe side throughout) and at each step join the
        connected relation with the smallest estimated cardinality, so
        build sides stay small and selective dimensions reduce the probe
        early. This is the greedy core of Trino's ReorderJoins
        (iterative/rule/ReorderJoins.java:97) driven by the row-count /
        selectivity estimates in estimate_rows (cost/StatsCalculator's
        role)."""
        pending = [self.apply_local_filters(r, conjuncts)
                   for r in relations]
        if 2 < len(pending) <= self.DP_REORDER_MAX:
            planned = self._dp_reorder(pending, conjuncts)
            if planned is not None:
                return self._maybe_multijoin(planned)
        pending.sort(key=lambda r: -self.estimate_rows(r.node))
        acc = pending.pop(0)
        while pending:
            connected = [r for r in pending
                         if self.has_equi_edge(acc, r, conjuncts)]
            if not connected:
                # cross join (NestedLoopJoinOperator's role): join on a
                # synthesized constant key so the expansion kernel
                # produces the cartesian product — the common shape is
                # single-row aggregate subqueries placed side by side
                # (TPC-DS q28/q88)
                chosen = min(pending, key=lambda r:
                             self.estimate_rows(r.node))
                pending.remove(chosen)
                acc = self.cross_join_pair(acc, chosen)
                acc = self.apply_local_filters(acc, conjuncts)
                continue
            chosen = min(connected, key=lambda r:
                         self.join_output_estimate(acc, r, conjuncts))
            pending.remove(chosen)
            acc = self.join_pair(acc, chosen, conjuncts, kind="inner")
            acc = self.apply_local_filters(acc, conjuncts)
        return self._maybe_multijoin(acc)

    def _maybe_multijoin(self, rel: PlannedRelation) -> PlannedRelation:
        """Star detector (ISSUE round-17): fuse the ladder's longest
        fact-to-dims prefix into a MultiJoinNode when the session allows
        it.  The rewrite is plan-shape only — the executor owns every
        runtime degrade back to the pairwise path."""
        from ..ops.pallas_hash import resolve_mode
        setting = self.properties.get("enable_multiway_join", "auto")
        if resolve_mode(setting) == "off":
            return rel
        max_dims = int(self.properties.get("multiway_max_dims", 5))
        fused = L.fuse_star_joins(rel.node, max_dims)
        if fused is rel.node:
            return rel
        return PlannedRelation(fused, rel.scope)

    # cost-based join reordering explores all connected bushy splits up
    # to this many relations (2^n subsets; TPC-DS join graphs past ~10
    # relations fall back to the greedy order)
    DP_REORDER_MAX = 10

    def _dp_reorder(self, pending, conjuncts) -> \
            Optional[PlannedRelation]:
        """Cost-based bushy join reordering (ReorderJoins.java:97 /
        IterativeOptimizer's memo, reduced to a subset DP: each memo
        group is a relation subset; the winning split per group is the
        plan). Cardinalities come from stats.py NDVs with the standard
        independence assumption; cost = probe rows + 2x build rows +
        output rows per join, summed over the tree. Unlike the greedy
        left-deep order, a selective dimension can join a dimension
        FIRST (bushy build subtrees) — TPC-H q5's orders x customer build
        side is the canonical win. None = graph disconnected (caller's
        greedy handles cross joins) or no stats-resolvable edges."""
        n = len(pending)
        rows = [max(1.0, self.estimate_rows(r.node)) for r in pending]
        stats = [self.chain_column_stats(r.node) for r in pending]

        # edges[(i, j)] = [(denominator, uniq_i, uniq_j)] per conjunct:
        # denominator is the max-NDV cardinality reduction; uniq_* says
        # that side is provably unique on its end of the edge (the FK ->
        # unique-PK direction), which is what makes a dense single-key
        # build possible
        edges: Dict[Tuple[int, int], List[Tuple[float, bool, bool]]] = {}
        for c in conjuncts:
            eq = as_equi(c)
            if eq is None:
                continue
            a, b = eq
            for i in range(n):
                for j in range(i + 1, n):
                    for x, y in ((a, b), (b, a)):
                        ci = pending[i].scope.try_resolve(x)
                        cj = pending[j].scope.try_resolve(y)
                        if ci is None or cj is None:
                            continue
                        ndvs = [max(1.0, s.ndv) for s in (
                            stats[i].get(ci.index) if stats[i] else None,
                            stats[j].get(cj.index) if stats[j] else None)
                            if s is not None]
                        denom = max(ndvs) if ndvs else \
                            min(rows[i], rows[j])
                        edges.setdefault((i, j), []).append(
                            (max(1.0, denom),
                             self.is_unique(pending[i], [ci.index]),
                             self.is_unique(pending[j], [cj.index])))
                        break
        if not edges:
            return None

        def n1_closed(mask: int, anchor: int) -> bool:
            """True if every relation in `mask` is reachable from
            `anchor` via N:1 edges (each hop lands on a side unique on
            its edge column) — then the subset joined in anchor-rooted
            order has at most one row per anchor row, so it stays unique
            on anchor's keys."""
            seen = 1 << anchor
            grew = True
            while grew:
                grew = False
                for (i, j), metas in edges.items():
                    if not ((mask >> i) & 1 and (mask >> j) & 1):
                        continue
                    for _, ui, uj in metas:
                        if (seen >> i) & 1 and not (seen >> j) & 1 and uj:
                            seen |= 1 << j
                            grew = True
                        if (seen >> j) & 1 and not (seen >> i) & 1 and ui:
                            seen |= 1 << i
                            grew = True
            return seen & mask == mask

        def split_is_dense(a: int, b: int) -> bool:
            """A cross edge whose build end is unique AND whose build
            subset is N:1-closed from that end admits a single-key dense
            unique-build join (key minimization drops other edges)."""
            for probe_m, build_m in ((a, b), (b, a)):
                for (i, j), metas in edges.items():
                    for _, ui, uj in metas:
                        if (probe_m >> i) & 1 and (build_m >> j) & 1 \
                                and uj and n1_closed(build_m, j):
                            return True
                        if (probe_m >> j) & 1 and (build_m >> i) & 1 \
                                and ui and n1_closed(build_m, i):
                            return True
            return False

        def connected(mask: int) -> bool:
            first = (mask & -mask).bit_length() - 1
            seen = 1 << first
            frontier = [first]
            while frontier:
                u = frontier.pop()
                for v in range(n):
                    if not (mask >> v) & 1 or (seen >> v) & 1:
                        continue
                    e = (min(u, v), max(u, v))
                    if e in edges:
                        seen |= 1 << v
                        frontier.append(v)
            return seen == mask
        full = (1 << n) - 1
        if not connected(full):
            return None

        # per-subset cardinality: product of base rows over the standard
        # 1/max-NDV reduction for every internal equi edge — identical
        # for every split of the subset, so the DP is well-defined
        card: List[float] = [0.0] * (1 << n)
        for mask in range(1, 1 << n):
            est = 1.0
            for i in range(n):
                if (mask >> i) & 1:
                    est *= rows[i]
            for (i, j), metas in edges.items():
                if (mask >> i) & 1 and (mask >> j) & 1:
                    for d, _, _ in metas:
                        est /= d
            card[mask] = max(1.0, est)

        # probe work scales with the probe side's BATCH CAPACITY, which
        # stays at the largest base relation's size along the fact spine
        # (the chunked loop never compacts), not with the post-join
        # cardinality — cost probes by the dominant base row count
        maxbase = [0.0] * (1 << n)
        for mask in range(1, 1 << n):
            i = (mask & -mask).bit_length() - 1
            rest = mask ^ (1 << i)
            maxbase[mask] = max(rows[i], maxbase[rest])

        INF = float("inf")
        cost = [INF] * (1 << n)
        split: List[Optional[Tuple[int, int]]] = [None] * (1 << n)
        for i in range(n):
            cost[1 << i] = 0.0
        for mask in range(1, 1 << n):
            if mask & (mask - 1) == 0 or not connected(mask):
                continue
            # enumerate proper sub-splits (A, B); A keeps the lowest bit
            # so each unordered split is visited once
            low = mask & -mask
            sub = (mask - 1) & mask
            while sub:
                a, b = sub, mask ^ sub
                if (a & low) and cost[a] < INF and cost[b] < INF and \
                        any(((a >> i) & 1) != ((a >> j) & 1)
                            for (i, j) in edges
                            if (mask >> i) & 1 and (mask >> j) & 1):
                    if card[a] >= card[b]:
                        probe_m, build_m = a, b
                    else:
                        probe_m, build_m = b, a
                    probe_r = max(card[probe_m], maxbase[probe_m])
                    build_r = card[build_m]
                    # non-dense joins (multi-key or no unique build) run
                    # the sorted kernels — measured ~4-10x the dense
                    # LUT's gather cost on this backend, so weigh them
                    # out of contention unless nothing dense exists.
                    # Probe rows weigh 3x: every probe-side join costs
                    # 2-3 HBM gathers per probe row (the measured
                    # bottleneck), so folding dimensions into build
                    # subtrees (fewer fact-side joins) wins even when it
                    # grows the build a little.
                    factor = 1.0 if split_is_dense(a, b) else 6.0
                    c = cost[a] + cost[b] + \
                        factor * (3.0 * probe_r + 2.0 * build_r) + \
                        card[mask]
                    if c < cost[mask]:
                        cost[mask] = c
                        split[mask] = (a, b)
                sub = (sub - 1) & mask
            if split[mask] is None:
                return None       # connected mask with no connected
                                  # split: bail to the greedy order

        def rec(mask: int) -> PlannedRelation:
            if mask & (mask - 1) == 0:
                return pending[mask.bit_length() - 1]
            a, b = split[mask]
            # larger estimated side goes left (probe): join_pair flips
            # to the unique side for the build anyway, but left-ness
            # decides which side stays the streaming spine
            if card[a] < card[b]:
                a, b = b, a
            out = self.join_pair(rec(a), rec(b), conjuncts, kind="inner")
            return self.apply_local_filters(out, conjuncts)

        return rec(full)

    def join_output_estimate(self, acc: PlannedRelation,
                             r: PlannedRelation, conjuncts) -> float:
        """Estimated |acc join r| — the greedy reorder cost (the
        ReorderJoins objective reduced to output cardinality). With no
        key stats it degrades to the build-side row count (the round-1
        smallest-build heuristic)."""
        rows_r = self.estimate_rows(r.node)
        denom = None
        astats = self.chain_column_stats(acc.node)
        rstats = self.chain_column_stats(r.node)
        for c in conjuncts:
            eq = as_equi(c)
            if eq is None:
                continue
            a, b = eq
            for x, y in ((a, b), (b, a)):
                ca = acc.scope.try_resolve(x)
                cr = r.scope.try_resolve(y)
                if ca is None or cr is None:
                    continue
                ndvs = [max(1.0, s.ndv) for s in (
                    astats.get(ca.index) if astats else None,
                    rstats.get(cr.index) if rstats else None)
                    if s is not None]
                if ndvs:
                    m = max(ndvs)
                    denom = m if denom is None else max(denom, m)
        if denom is None:
            return rows_r
        rows_a = self.estimate_rows(acc.node)
        return max(1.0, rows_a * rows_r / denom)

    def plan_unnest(self, rel: PlannedRelation,
                    u: A.UnnestRef) -> PlannedRelation:
        """Lateral UNNEST over the combined preceding relations
        (tree/Unnest.java -> UnnestOperator.java:42)."""
        lowerer = ExpressionLowerer(rel.scope, planner=self)
        arg = lowerer.lower(u.arg)
        if arg.dtype.kind is not TypeKind.ARRAY:
            raise AnalysisError("UNNEST argument must be an array")
        fld = self.field_for(arg, rel.scope)
        if fld is None or fld.dictionary is None:
            raise AnalysisError("UNNEST array lost its element pool")
        node = rel.node
        if isinstance(arg, ir.ColumnRef):
            array_col = arg.index
        else:
            exprs = tuple(ir.ColumnRef(i, dt) for i, (_, dt)
                          in enumerate(node.output)) + (arg,)
            out = tuple(node.output) + (("$unnest_arr", arg.dtype),)
            node = L.ProjectNode(node, exprs, out)
            array_col = len(out) - 1

        elem_t = arg.dtype.element
        elem_name = (u.colnames[0] if u.colnames else "$unnest").lower()
        elem_pool = None
        if elem_t.kind is TypeKind.VARCHAR:
            elem_pool = tuple(sorted(
                {v for tup in fld.dictionary for v in tup
                 if v is not None}))
        output = tuple(node.output) + ((elem_name, elem_t),)
        if u.ordinality:
            ord_name = (u.colnames[1] if u.colnames and
                        len(u.colnames) > 1 else "ordinality").lower()
            output = output + ((ord_name, BIGINT),)
        unnest = L.UnnestNode(node, array_col, tuple(fld.dictionary),
                              elem_name, elem_t, elem_pool, u.ordinality,
                              output)
        alias = (u.alias or "$unnest").lower()
        n0 = len(node.output)
        cols = list(rel.scope.columns)
        elem_field = Field(elem_name, elem_t, dictionary=elem_pool)
        cols.append(ScopeColumn(alias, elem_name, elem_t, n0, elem_field))
        if u.ordinality:
            cols.append(ScopeColumn(alias, output[-1][0], BIGINT,
                                    n0 + 1, None))
        return PlannedRelation(unnest, Scope(cols))

    def cross_join_pair(self, left: PlannedRelation,
                        right: PlannedRelation) -> PlannedRelation:
        """Cartesian product via a constant-key equi-join: both sides gain
        a $ck=0 column; the expansion kernel's 1:N fan-out does the rest.
        The appended key columns stay out of the scope (like make_join's
        remapped varchar keys)."""
        zero = ir.Literal(0, BIGINT)

        def with_key(node: L.PlanNode):
            exprs = tuple(ir.ColumnRef(i, dt)
                          for i, (_, dt) in enumerate(node.output))
            out = tuple(node.output) + (("$ck", BIGINT),)
            return L.ProjectNode(node, exprs + (zero,), out), \
                len(node.output)

        pnode, pk = with_key(left.node)
        bnode, bk = with_key(right.node)
        out = tuple(pnode.output) + tuple(bnode.output)
        node = L.JoinNode("inner", pnode, bnode, (pk,), (bk,), None,
                          False, out)
        n_left = len(pnode.output)
        cols = list(left.scope.columns) + [
            ScopeColumn(c.qualifier, c.name, c.dtype, c.index + n_left,
                        c.field) for c in right.scope.columns]
        return PlannedRelation(node, Scope(cols))

    # ---- cardinality estimation (cost/StatsCalculator.java:22's role) --

    FILTER_SELECTIVITY = {"=": 0.05, "<>": 0.9, "<": 0.3, "<=": 0.3,
                          ">": 0.3, ">=": 0.3}

    def estimate_rows(self, node: L.PlanNode) -> float:
        if isinstance(node, L.ScanNode):
            stats = self.catalog.get_table_stats(
                node.catalog, node.schema_name, node.table)
            if stats is not None:
                return float(stats.row_count)
            return 1e6
        if isinstance(node, L.FilterNode):
            return self.estimate_rows(node.child) * \
                self.predicate_selectivity(
                    node.predicate, self.chain_column_stats(node.child))
        if isinstance(node, (L.ProjectNode, L.WindowNode, L.SortNode)):
            return self.estimate_rows(node.child)
        if isinstance(node, L.LimitNode):
            return min(float(node.count), self.estimate_rows(node.child))
        if isinstance(node, L.AggregateNode):
            if not node.group_keys:
                return 1.0
            child_rows = self.estimate_rows(node.child)
            ndv = self.group_ndv_product(node)
            if ndv is not None:
                return max(1.0, min(child_rows, ndv))
            return max(1.0, child_rows / 10)
        if isinstance(node, L.JoinNode):
            probe = self.estimate_rows(node.left)
            if node.kind in ("semi", "anti"):
                return probe * 0.5
            if node.kind == "mark":
                return probe
            build = self.estimate_rows(node.right)
            key_ndv = self.join_key_ndv(node)
            if key_ndv is not None and key_ndv > 0:
                # |L join R| ~= |L|*|R| / max(ndv) (JoinStatsRule)
                return max(1.0, probe * build / key_ndv)
            return probe if node.build_unique else probe * 2
        if isinstance(node, L.ValuesNode):
            return float(node.num_rows)
        if isinstance(node, L.SetOpNode):
            return self.estimate_rows(node.left) + \
                self.estimate_rows(node.right)
        return 1e6

    def chain_column_stats(self, node: L.PlanNode):
        """Per-output-column ColumnStats for Filter/Project/Join trees
        over scans (None where unknown). Joins concatenate probe++build
        column stats (NDVs are upper bounds post-join — callers cap by
        row estimates). The seam where connector statistics enter the
        cost model (spi/statistics -> FilterStatsCalculator)."""
        chain = []
        while isinstance(node, (L.FilterNode, L.ProjectNode)):
            chain.append(node)
            node = node.child
        if isinstance(node, L.JoinNode):
            left = self.chain_column_stats(node.left) or {}
            cur = dict(left)
            if node.kind in ("inner", "left"):
                right = self.chain_column_stats(node.right) or {}
                n_probe = len(node.left.output)
                for i, s in right.items():
                    cur[n_probe + i] = s
        elif isinstance(node, L.ScanNode):
            stats = self.catalog.get_table_stats(
                node.catalog, node.schema_name, node.table)
            if stats is None:
                return None
            cur = {}
            for i, ci in enumerate(node.column_indices):
                cur[i] = stats.columns.get(
                    node.table_schema.fields[ci].name)
        else:
            return None
        for nd in reversed(chain):
            if isinstance(nd, L.ProjectNode):
                cur = {i: cur.get(e.index)
                       if isinstance(e, ir.ColumnRef) else None
                       for i, e in enumerate(nd.exprs)}
        return cur

    def join_key_ndv(self, node: L.JoinNode):
        """max NDV across the equi-key pair (the join-size denominator)."""
        lstats = self.chain_column_stats(node.left)
        rstats = self.chain_column_stats(node.right)
        best = None
        for lk, rk in zip(node.left_keys, node.right_keys):
            ln = lstats.get(lk) if lstats else None
            rn = rstats.get(rk) if rstats else None
            ndvs = [s.ndv for s in (ln, rn) if s is not None]
            if ndvs:
                m = max(ndvs)
                best = m if best is None else max(best, m)
        return best

    def group_ndv_product(self, node: L.AggregateNode):
        cstats = self.chain_column_stats(node.child)
        if cstats is None:
            return None
        prod = 1.0
        for k in node.group_keys:
            s = cstats.get(k)
            if s is None:
                return None
            prod *= max(1.0, s.ndv)
        return prod

    def predicate_selectivity(self, pred: ir.Expr,
                              colstats=None) -> float:
        """Selectivities: dictionary predicates are near-exact (fraction
        of pool values passing); numeric comparisons interpolate against
        column min/max + NDV when stats are known, else fall back to the
        fixed heuristics (FilterStatsCalculator's structure)."""
        if isinstance(pred, ir.DictPredicate):
            if len(pred.lut) == 0:
                return 0.1
            return max(0.01, sum(pred.lut) / len(pred.lut))
        if isinstance(pred, ir.Compare):
            s = self._stats_compare_selectivity(pred, colstats)
            if s is not None:
                return s
            return self.FILTER_SELECTIVITY.get(pred.op, 0.33)
        if isinstance(pred, ir.Between):
            s = self._range_fraction(pred.arg, pred.low, pred.high,
                                     colstats)
            return s if s is not None else 0.25
        if isinstance(pred, ir.InList):
            cs = self._col_stats(pred.arg, colstats)
            if cs is not None and cs.ndv > 0:
                return min(1.0, len(pred.values) / cs.ndv)
            return min(0.9, 0.05 * len(pred.values))
        if isinstance(pred, ir.Logical):
            parts = [self.predicate_selectivity(a, colstats)
                     for a in pred.args]
            if pred.op == "and":
                out = 1.0
                for p in parts:
                    out *= p
                return out
            out = 0.0
            for p in parts:
                out = out + p - out * p
            return out
        if isinstance(pred, ir.Not):
            return 1.0 - self.predicate_selectivity(pred.arg, colstats)
        return 0.33

    @staticmethod
    def _col_stats(e: ir.Expr, colstats):
        if colstats is None or not isinstance(e, ir.ColumnRef):
            return None
        return colstats.get(e.index)

    def _stats_compare_selectivity(self, pred: ir.Compare, colstats):
        col, lit = pred.left, pred.right
        op = pred.op
        if isinstance(col, ir.Literal) and isinstance(lit, ir.ColumnRef):
            col, lit = lit, col
            op = flip(op)
        if not isinstance(lit, ir.Literal) or lit.value is None:
            return None
        cs = self._col_stats(col, colstats)
        if cs is None:
            return None
        if op == '=':
            return 1.0 / max(1.0, cs.ndv)
        if op == '<>':
            return 1.0 - 1.0 / max(1.0, cs.ndv)
        if cs.min_val is None or cs.max_val is None or \
                cs.max_val <= cs.min_val:
            return None
        try:
            v = float(lit.value)
            # column stats are over the stored (scaled-int) decimal
            # representation; normalize the literal to the column's scale
            v *= 10.0 ** (_scale_of(col.dtype) - _scale_of(lit.dtype))
        except (TypeError, ValueError):
            return None
        frac = (v - cs.min_val) / (cs.max_val - cs.min_val)
        frac = min(1.0, max(0.0, frac))
        return frac if op in ('<', '<=') else 1.0 - frac

    def _range_fraction(self, arg, low, high, colstats):
        cs = self._col_stats(arg, colstats)
        if cs is None or cs.min_val is None or cs.max_val is None or \
                cs.max_val <= cs.min_val or \
                not isinstance(low, ir.Literal) or \
                not isinstance(high, ir.Literal) or \
                low.value is None or high.value is None:
            return None
        try:
            ref = _scale_of(arg.dtype)
            lo = float(low.value) * 10.0 ** (ref - _scale_of(low.dtype))
            hi = float(high.value) * 10.0 ** (ref - _scale_of(high.dtype))
        except (TypeError, ValueError):
            return None
        span = cs.max_val - cs.min_val
        frac = (min(hi, cs.max_val) - max(lo, cs.min_val)) / span
        return min(1.0, max(0.0, frac))

    def has_equi_edge(self, left: PlannedRelation, right: PlannedRelation,
                      conjuncts: List[A.Node]) -> bool:
        for c in conjuncts:
            eq = as_equi(c)
            if eq is None:
                continue
            a, b = eq
            if (left.scope.try_resolve(a) and right.scope.try_resolve(b)) or \
               (left.scope.try_resolve(b) and right.scope.try_resolve(a)):
                return True
        return False

    def apply_local_filters(self, rel: PlannedRelation,
                            conjuncts: List[A.Node]) -> PlannedRelation:
        """Push down any pending conjunct fully resolvable in this scope."""
        applied = []
        preds = []
        for c in conjuncts:
            lowerer = ExpressionLowerer(rel.scope, planner=self)
            try:
                preds.append(lowerer.to_bool(lowerer.lower(c)))
                applied.append(c)
            except AnalysisError:
                continue
        for c in applied:
            conjuncts.remove(c)
        if not preds:
            return rel
        pred = preds[0] if len(preds) == 1 else ir.Logical(
            "and", tuple(preds))
        node = L.FilterNode(rel.node, pred, rel.node.output)
        return PlannedRelation(node, rel.scope)

    def join_pair(self, left: PlannedRelation, right: PlannedRelation,
                  conjuncts: List[A.Node], kind: str) -> PlannedRelation:
        """Extract equi-conjuncts linking left & right; orient probe/build."""
        left_keys: List[int] = []
        right_keys: List[int] = []
        used: List[A.Node] = []
        for c in conjuncts:
            eq = as_equi(c)
            if eq is None:
                continue
            a, b = eq
            la = left.scope.try_resolve(a)
            rb = right.scope.try_resolve(b)
            if la is not None and rb is not None:
                left_keys.append(la.index)
                right_keys.append(rb.index)
                used.append(c)
                continue
            lb = left.scope.try_resolve(b)
            ra = right.scope.try_resolve(a)
            if lb is not None and ra is not None:
                left_keys.append(lb.index)
                right_keys.append(ra.index)
                used.append(c)
        if not left_keys:
            raise AnalysisError(
                "cross join without equi-condition not yet supported")

        # Key minimization (inner joins): when several equi edges link the
        # two sides, using them ALL as join keys forces the multi-column
        # packed-key kernels (sorted path — no dense LUT). If ONE key pair
        # alone proves build uniqueness with a dense domain, join on just
        # that key and leave the other equalities in `conjuncts` — the
        # caller's apply_local_filters turns them into a (free) post-join
        # filter. TPC-H q5's c_custkey=o_custkey AND c_nationkey=
        # s_nationkey is the canonical shape: the nationkey equality
        # becomes a filter, keeping every join single-key dense.
        if kind == "inner" and len(left_keys) > 1:
            for j in range(len(left_keys)):
                for a, b, ak, bk in ((left, right, left_keys, right_keys),
                                     (right, left, right_keys, left_keys)):
                    if not self.is_unique(b, [bk[j]]):
                        continue
                    dom = self._dense_key_domain(
                        b.node, [bk[j]],
                        [self._scope_field(b.scope, bk[j])])
                    if dom is None:
                        continue
                    used = [used[j]]
                    left_keys = [left_keys[j]]
                    right_keys = [right_keys[j]]
                    break
                else:
                    continue
                break
        for c in used:
            conjuncts.remove(c)

        # orientation: build side should be unique on its keys if provable;
        # LEFT joins pin the preserved side as probe (no freedom)
        right_unique = self.is_unique(right, right_keys)
        left_unique = self.is_unique(left, left_keys)
        if kind == "left" or right_unique or not left_unique:
            probe, build = left, right
            probe_keys, build_keys = left_keys, right_keys
            build_unique = right_unique
        else:
            probe, build = right, left
            probe_keys, build_keys = right_keys, left_keys
            build_unique = left_unique

        node = self.make_join(
            kind, probe.node, build.node, probe_keys, build_keys, None,
            build_unique,
            probe_fields=[self._scope_field(probe.scope, i)
                          for i in probe_keys],
            build_fields=[self._scope_field(build.scope, i)
                          for i in build_keys])
        n_left = len(probe.node.output)
        cols = list(probe.scope.columns) + [
            ScopeColumn(c.qualifier, c.name, c.dtype, c.index + n_left,
                        c.field) for c in build.scope.columns]
        return PlannedRelation(node, Scope(cols))

    @staticmethod
    def _scope_field(scope: Scope, index: int) -> Optional[Field]:
        for c in scope.columns:
            if c.index == index:
                return c.field
        return None

    def make_join(self, kind: str, probe_node: L.PlanNode,
                  build_node: L.PlanNode, probe_keys, build_keys,
                  residual, build_unique: bool, *,
                  probe_fields, build_fields,
                  null_aware: bool = False) -> L.JoinNode:
        """THE JoinNode constructor: every join-building path funnels
        through here so varchar keys always get dictionary alignment.

        Codes only match within one pool; where a key pair is
        varchar-vs-varchar with differing pools, the build side gains an
        appended BIGINT key column remapping its codes into the probe pool
        (-1 = absent, matches no valid code) — the dictionary-aware twin
        of Trino's type-coerced join clauses."""
        probe_keys = list(probe_keys)
        build_keys = list(build_keys)
        build_key_domain = self._dense_key_domain(
            build_node, build_keys, build_fields)
        extra: List[ir.Expr] = []
        extra_cols: List[Tuple[str, DataType]] = []
        nb = len(build_node.output)
        for i, (pf, bf) in enumerate(zip(probe_fields, build_fields)):
            pk, bk0 = probe_keys[i], build_keys[i]
            p_varchar = probe_node.output[pk][1].kind is TypeKind.VARCHAR
            b_varchar = build_node.output[bk0][1].kind is TypeKind.VARCHAR
            if not (p_varchar and b_varchar):
                continue
            lpool = pf.dictionary if pf is not None else None
            rpool = bf.dictionary if bf is not None else None
            if lpool is None or rpool is None:
                # silent code-matching would be wrong — refuse loudly
                raise AnalysisError(
                    "varchar join key lost its dictionary; cannot align "
                    "pools")
            if lpool == rpool:
                continue
            bk = build_keys[i]
            dt = build_node.output[bk][1]
            extra.append(ir.DictValueMap(ir.ColumnRef(bk, dt),
                                         _remap_lut(lpool, rpool), BIGINT))
            extra_cols.append((f"$jk{len(extra_cols)}", BIGINT))
            build_keys[i] = nb + len(extra) - 1
        if extra:
            exprs = tuple(
                [ir.ColumnRef(j, dt) for j, (_, dt)
                 in enumerate(build_node.output)] + extra)
            build_node = L.ProjectNode(
                build_node, exprs,
                tuple(build_node.output) + tuple(extra_cols))
        if kind in ("inner", "left"):
            output = tuple(probe_node.output) + tuple(build_node.output)
        elif kind == "mark":
            # mark join: probe columns + the EXISTS truth column
            output = tuple(probe_node.output) + (("$mark", BOOLEAN),)
        else:
            output = tuple(probe_node.output)
        # DetermineJoinDistributionType.java:51's choice, by estimated
        # build bytes: small builds replicate over the mesh (all_gather),
        # large ones hash-repartition both sides (all_to_all). The
        # session can force either (join_distribution_type).
        forced = self.properties.get("join_distribution_type", "auto")
        if forced in ("broadcast", "partitioned"):
            distribution = forced
        elif kind != "inner" or residual is not None or null_aware:
            # only inner equi-joins can co-partition on the mesh today;
            # predicting "partitioned" for shapes the executor must
            # demote would make every EXPLAIN verdict a miss
            distribution = "broadcast"
        else:
            threshold_mb = self.properties.get(
                "broadcast_join_threshold_mb", 32)
            build_bytes = self.estimate_rows(build_node) * \
                max(1, len(build_node.output)) * 8
            distribution = "broadcast" \
                if build_bytes < (threshold_mb << 20) else "partitioned"
        if extra:
            build_key_domain = None    # remapped varchar keys can be -1
        return L.JoinNode(kind, probe_node, build_node,
                          tuple(probe_keys), tuple(build_keys), residual,
                          build_unique, output, null_aware=null_aware,
                          distribution=distribution,
                          build_key_domain=build_key_domain)

    # dense-LUT memory caps: absolute 2^30 entries (4GB of int32), and
    # 256x the build rows so only wildly sparse domains stay on the
    # sorted path. The sparsity cap is deliberately loose: scatter cost
    # is O(domain memset + rows) and probe cost is O(probe gathers) —
    # both independent of sparsity — so the only real cost of a sparse
    # LUT is HBM, and a measured 33M-probe dense join runs ~2s where the
    # sorted fallback takes ~60s. (A cost-reordered bushy build side is
    # often SMALL relative to its key domain — a 16x cap silently
    # knocked those joins off the dense path.)
    _DENSE_DOMAIN_CAP = 1 << 30

    def _dense_key_domain(self, build_node, build_keys, build_fields):
        """Static [0, domain) bound for a single build key, from exact
        connector min/max stats (integer keys) or the dictionary pool
        size (same-pool varchar keys)."""
        if len(build_keys) != 1:
            return None
        bk = build_keys[0]
        dt = build_node.output[bk][1]
        if dt.kind is TypeKind.VARCHAR:
            bf = build_fields[0]
            if bf is not None and bf.dictionary is not None:
                return max(1, len(bf.dictionary))
            return None
        if dt.kind not in (TypeKind.BIGINT, TypeKind.INTEGER,
                           TypeKind.DATE):
            return None
        cstats = self.chain_column_stats(build_node)
        s = cstats.get(bk) if cstats else None
        if s is None or s.min_val is None or s.min_val < 0:
            return None
        d = int(s.max_val) + 2
        rows = self.estimate_rows(build_node)
        if d > self._DENSE_DOMAIN_CAP or d > max(1 << 22, 256 * rows):
            return None
        return 1 << (d - 1).bit_length()      # pow2: stable jit cache

    def plan_left_join(self, left: PlannedRelation, right: PlannedRelation,
                       condition: Optional[A.Node]) -> PlannedRelation:
        conjuncts: List[A.Node] = []
        if condition is not None:
            split_conjuncts(condition, conjuncts)
        # ON conjuncts referencing only the build side filter the match
        # candidates, never the preserved side — push them into the build
        # input (Trino PredicatePushDown's inner-side pushdown for outer
        # joins). Preserved-side-only ON conjuncts cannot be pushed.
        right = self.apply_local_filters(right, conjuncts)
        rel = self.join_pair(left, right, conjuncts, kind="left")
        if conjuncts:
            raise AnalysisError("non-equi LEFT JOIN condition unsupported")
        return rel

    def plan_right_join(self, left: PlannedRelation,
                        right: PlannedRelation,
                        condition: Optional[A.Node]) -> PlannedRelation:
        """RIGHT JOIN = LEFT JOIN with sides swapped, re-projected back to
        (left columns, right columns) order (Trino's planner performs the
        same flip — there is no RIGHT at the operator level)."""
        rel = self.plan_left_join(right, left, condition)
        n_right = len(right.node.output)
        total = len(rel.node.output)
        perm = list(range(n_right, total)) + list(range(n_right))
        exprs = tuple(ir.ColumnRef(p, rel.node.output[p][1]) for p in perm)
        output = tuple(rel.node.output[p] for p in perm)
        node = L.ProjectNode(rel.node, exprs, output)
        new_pos = {old: new for new, old in enumerate(perm)}
        cols = sorted((ScopeColumn(c.qualifier, c.name, c.dtype,
                                   new_pos[c.index], c.field)
                       for c in rel.scope.columns),
                      key=lambda c: c.index)
        return PlannedRelation(node, Scope(cols))

    def plan_full_join(self, left: PlannedRelation,
                       right: PlannedRelation,
                       condition: Optional[A.Node]) -> PlannedRelation:
        """FULL JOIN = LEFT JOIN union-all (right rows with no match,
        NULL-padded on the left) — the lowering Trino reaches via
        LookupJoinOperator + LookupOuterOperator, expressed set-at-a-time."""
        conjuncts: List[A.Node] = []
        if condition is not None:
            split_conjuncts(condition, conjuncts)
        lj = self.join_pair(left, right, conjuncts, kind="left")
        if conjuncts:
            raise AnalysisError("non-equi FULL JOIN condition unsupported")
        # the left-join output may carry appended $jk alignment columns;
        # project back to the visible (left ++ right) layout for the union
        n_vis = len(left.node.output) + len(right.node.output)
        lj_node: L.PlanNode = lj.node
        if len(lj_node.output) != n_vis:
            lj_node = L.ProjectNode(
                lj_node,
                tuple(ir.ColumnRef(i, dt)
                      for i, (_, dt) in enumerate(lj_node.output[:n_vis])),
                tuple(lj_node.output[:n_vis]))
        # right rows with no left match (anti join, probe = right)
        conj2: List[A.Node] = []
        if condition is not None:
            split_conjuncts(condition, conj2)
        rk: List[int] = []
        lk: List[int] = []
        for c in list(conj2):
            eq = as_equi(c)
            if eq is None:
                continue
            a, b = eq
            ra, lb = right.scope.try_resolve(a), left.scope.try_resolve(b)
            if ra is not None and lb is not None:
                rk.append(ra.index)
                lk.append(lb.index)
                continue
            rb, la = right.scope.try_resolve(b), left.scope.try_resolve(a)
            if rb is not None and la is not None:
                rk.append(rb.index)
                lk.append(la.index)
        anti = self.make_join(
            "anti", right.node, left.node, tuple(rk), tuple(lk), None,
            False,
            probe_fields=[self._scope_field(right.scope, i) for i in rk],
            build_fields=[self._scope_field(left.scope, i) for i in lk])
        pad_exprs = tuple(
            [ir.Literal(None, dt) for _, dt in left.node.output] +
            [ir.ColumnRef(i, dt)
             for i, (_, dt) in enumerate(right.node.output)])
        pad = L.ProjectNode(anti, pad_exprs, lj_node.output)
        none_maps = (None,) * len(lj_node.output)
        full = L.SetOpNode("union_all", lj_node, pad, none_maps,
                           none_maps, lj_node.output)
        return PlannedRelation(full, lj.scope)

    def is_unique(self, rel: PlannedRelation, key_indices: List[int]) -> bool:
        return self.node_unique_on(rel.node, frozenset(key_indices))

    def node_unique_on(self, node: L.PlanNode, keys: frozenset) -> bool:
        """True if `node`'s output is provably unique on the given column
        positions. The planner's stand-in for Trino's stats-derived
        distinct-count reasoning (DetermineJoinDistributionType.java:51):
        primary keys at scans, propagated through filters, unique-build
        joins (probe multiplicity preserved) and aggregations (output is
        unique on its group keys)."""
        if isinstance(node, (L.FilterNode, L.SortNode, L.LimitNode)):
            return self.node_unique_on(node.child, keys)
        if isinstance(node, L.ProjectNode):
            mapped = set()
            for i in keys:
                e = node.exprs[i]
                if not isinstance(e, ir.ColumnRef):
                    return False
                mapped.add(e.index)
            return self.node_unique_on(node.child, frozenset(mapped))
        if isinstance(node, L.ScanNode):
            data = self.catalog.get_table(node.catalog, node.schema_name,
                                          node.table)
            if not data.primary_key:
                return False
            key_names = {node.output[i][0].lower() for i in keys}
            return set(k.lower() for k in data.primary_key) <= key_names
        if isinstance(node, L.JoinNode):
            if node.kind in ("inner", "left") and node.build_unique:
                n_probe = len(node.left.output)
                if all(i < n_probe for i in keys):
                    return self.node_unique_on(node.left, keys)
            if node.kind in ("semi", "anti"):
                return self.node_unique_on(node.left, keys)
            return False
        if isinstance(node, L.AggregateNode):
            n_group = len(node.group_keys)
            return set(range(n_group)) <= keys
        return False

    # ------------------------------------------------------------------
    # query planning
    # ------------------------------------------------------------------

    def plan_query(self, q) -> PlannedRelation:
        if isinstance(q, A.Values):
            return self.plan_values_statement(q)
        saved_ctes = self.ctes
        if q.ctes:
            self.ctes = dict(self.ctes)
            for name, cq in q.ctes:
                self.ctes[name.lower()] = cq
        try:
            if isinstance(q, A.SetOp):
                return self.plan_setop(q)
            return self.plan_query_body(q)
        finally:
            self.ctes = saved_ctes

    def plan_query_body(self, q: A.Query) -> PlannedRelation:
        unnests: List[A.UnnestRef] = []
        if q.relation is None:
            # SELECT without FROM: single-row zero-column input relation
            # (Trino: Query with an implicit single-row ValuesNode)
            relations, on_conjuncts = [PlannedRelation(
                L.ValuesNode((), (), 1, (), ()), Scope([]))], []
        else:
            relations, on_conjuncts = self.plan_relation_tree(q.relation,
                                                              unnests)
        if not relations and unnests:
            # FROM UNNEST(ARRAY[...]) alone: expand a single-row input
            relations = [PlannedRelation(
                L.ValuesNode((), (), 1, (), ()), Scope([]))]

        conjuncts: List[A.Node] = list(on_conjuncts)
        if q.where is not None:
            split_conjuncts(q.where, conjuncts)
        add_or_common_conjuncts(conjuncts)

        if len(relations) == 1:
            rel = self.apply_local_filters(relations[0], conjuncts)
        else:
            rel = self.build_join_tree(relations, conjuncts)
        for u in unnests:
            rel = self.plan_unnest(rel, u)
            rel = self.apply_local_filters(rel, conjuncts)
        # residual multi-relation predicates (e.g. q19's OR-of-blocks)
        # become filters over the joined scope
        rel = self.apply_local_filters(rel, conjuncts)
        # subquery predicates: decorrelate to semi/anti/aggregate joins
        # (the role of Trino's TransformCorrelated* / TransformUncorrelated*
        # iterative rules, sql/planner/iterative/rule/)
        progress = True
        while progress and conjuncts:
            progress = False
            for c in list(conjuncts):
                new_rel = self.plan_subquery_conjunct(rel, c)
                if new_rel is not None:
                    conjuncts.remove(c)
                    rel = self.apply_local_filters(new_rel, conjuncts)
                    progress = True
                    break
        if conjuncts:
            raise AnalysisError(
                f"unplaced predicate(s): {conjuncts}")

        has_agg = any(contains_aggregate(i.expr) for i in q.select
                      if i.expr is not None) or q.group_by or \
            (q.having is not None)

        if has_agg:
            rel, select_scope_exprs, names = self.plan_aggregation(q, rel)
        else:
            rel, select_scope_exprs, names = self.plan_plain_select(q, rel)

        # DISTINCT via group-by-all-columns (Trino rewrites the same way)
        if q.distinct:
            node = rel.node
            ncols = len(node.output)
            rel = PlannedRelation(
                L.AggregateNode(node, tuple(range(ncols)), (), "sort", (),
                                DEFAULT_SORT_GROUPS, node.output),
                rel.scope)

        # ORDER BY over the select output scope (+ alias resolution);
        # expressions not in the select list become hidden sort columns
        # appended to the projection and dropped after the sort (Trino's
        # PruneOrderByInAggregation / hidden-symbol ordering scheme)
        if q.order_by:
            plain_from = self._plain_from
            proj = rel.node
            lower_hidden = None
            if (not has_agg and not q.distinct and
                    isinstance(proj, L.ProjectNode) and
                    plain_from is not None and
                    plain_from[0] is proj.child):
                _, from_scope, wslots = plain_from
                lower_hidden = ExpressionLowerer(
                    from_scope, planner=self, window_slots=wslots).lower
            else:
                # aggregation: the post-agg rewrite closure lowers
                # ORDER BY expressions over (group keys, agg slots,
                # grouping() columns)
                post_agg = getattr(self, "_post_agg", None)
                if not q.distinct and post_agg is not None and \
                        post_agg[0] is rel.node:
                    lower_hidden = post_agg[1]
            can_hide = lower_hidden is not None
            idxs = []
            for item in q.order_by:
                try:
                    idx = self.resolve_order_expr(item.expr, q, rel, names)
                except AnalysisError:
                    if not can_hide:
                        raise
                    idx = None
                idxs.append(idx)
            if any(i is None for i in idxs):
                exprs = list(proj.exprs)
                out_cols = list(proj.output)
                for k, item in enumerate(q.order_by):
                    if idxs[k] is None:
                        e = materialize_string(lower_hidden(item.expr))
                        exprs.append(e)
                        out_cols.append((f"$sort{len(out_cols)}", e.dtype))
                        idxs[k] = len(out_cols) - 1
                base: L.PlanNode = L.ProjectNode(proj.child, tuple(exprs),
                                                 tuple(out_cols))
            else:
                base = rel.node
            keys = []
            for idx, item in zip(idxs, q.order_by):
                nulls_first = item.nulls_first
                if nulls_first is None:
                    nulls_first = not item.ascending   # Trino default
                keys.append(L.SortKey(idx, item.ascending, nulls_first))
            sorted_node: L.PlanNode = L.SortNode(base, tuple(keys), q.limit,
                                                 base.output)
            if base is not rel.node:      # drop hidden sort columns
                sorted_node = L.ProjectNode(
                    sorted_node,
                    tuple(ir.ColumnRef(i, dt)
                          for i, (_, dt) in enumerate(proj.output)),
                    proj.output)
            rel = PlannedRelation(sorted_node, rel.scope)
        elif q.limit is not None:
            rel = PlannedRelation(
                L.LimitNode(rel.node, q.limit, rel.node.output), rel.scope)

        out = L.OutputNode(rel.node, tuple(names), rel.node.output)
        return PlannedRelation(out, rel.scope)

    # ---- plain select -----------------------------------------------------

    def expand_star(self, q: A.Query, scope: Scope):
        items = []
        for item in q.select:
            if item.expr is None:
                qual = None
                if item.star_qualifier:
                    qual = item.star_qualifier[-1].lower()
                for c in scope.columns:
                    if qual is None or c.qualifier == qual:
                        items.append((A.Identifier((c.qualifier, c.name)),
                                      c.name))
            else:
                name = item.alias or default_name(item.expr)
                items.append((item.expr, name.lower()))
        return items

    def plan_plain_select(self, q: A.Query, rel: PlannedRelation):
        items = self.expand_star(q, rel.scope)

        # window functions: plan WindowNode(s) below the final projection
        wcalls: List[A.WindowFunc] = []
        for ast, _ in items:
            self.collect_windows(ast, wcalls)
        for o in q.order_by:
            self.collect_windows(o.expr, wcalls)
        window_slots: Dict[A.WindowFunc, ir.Expr] = {}
        wfields: Dict[A.WindowFunc, Optional[Field]] = {}
        scope = rel.scope
        if wcalls:
            wl = ExpressionLowerer(scope, planner=self)
            node, window_slots, wfields = self.plan_windows(
                rel.node, wcalls, wl.lower, scope)
            rel = PlannedRelation(node, scope)

        lowerer = ExpressionLowerer(scope, planner=self,
                                    window_slots=window_slots)
        exprs = []
        names = []
        out_cols = []
        new_scope = []
        for i, (ast, name) in enumerate(items):
            e = materialize_string(lowerer.lower(ast))
            exprs.append(e)
            names.append(name)
            out_cols.append((name, e.dtype))
            fld = self.field_for(e, scope)
            if fld is None and isinstance(ast, A.WindowFunc):
                fld = wfields.get(ast)
            new_scope.append(ScopeColumn(None, name, e.dtype, i, fld))
        node = L.ProjectNode(rel.node, tuple(exprs), tuple(out_cols))
        self._plain_from = (rel.node, scope, window_slots)
        return PlannedRelation(node, Scope(new_scope)), exprs, names

    # ---- window functions -------------------------------------------------

    WINDOW_NAMES = {"row_number", "rank", "dense_rank", "ntile", "lead",
                    "lag", "first_value", "last_value"} | AGG_NAMES

    def collect_windows(self, node: A.Node, out: List[A.WindowFunc]) -> None:
        if isinstance(node, A.WindowFunc):
            if node.name not in self.WINDOW_NAMES:
                raise AnalysisError(
                    f"unsupported window function {node.name}()")
            if node not in out:
                out.append(node)
            return                    # args of a window call have no windows
        for ch in ast_children(node):
            self.collect_windows(ch, out)

    @staticmethod
    def frame_mode(call: A.WindowFunc) -> str:
        """SQL frame -> kernel frame (ops/window.py FRAMES)."""
        if not call.order_by:
            return "partition"
        f = call.frame
        if f is None:
            return "range_running"    # SQL default frame
        kind = "rows" if f.unit == "rows" else "range"
        if f.start != "unbounded_preceding":
            # bounded frames: (ROWS|RANGE) BETWEEN p PRECEDING AND
            # (CURRENT ROW | f FOLLOWING) — FramedWindowFunction's role.
            # RANGE bounds are VALUE offsets over the single numeric
            # ORDER BY key (WindowOperator.java:70 frame semantics).
            if f.start.endswith("_preceding") and f.start[0].isdigit():
                p = int(f.start.split("_")[0])
            elif f.start == "current_row":
                p = 0
            else:
                raise AnalysisError(
                    "only UNBOUNDED PRECEDING, n PRECEDING or CURRENT "
                    "ROW frame starts are supported")
            if f.end == "current_row":
                fl = 0
            elif f.end.endswith("_following") and f.end[0].isdigit():
                fl = int(f.end.split("_")[0])
            else:
                raise AnalysisError(
                    f"unsupported {f.unit.upper()} frame end {f.end!r}")
            return f"{kind}_bounded:{p}:{fl}"
        if f.end == "current_row":
            return "rows_running" if f.unit == "rows" else "range_running"
        if f.end.endswith("_following") and f.end[0].isdigit():
            fl = int(f.end.split("_")[0])
            if f.unit != "rows":
                # UNBOUNDED PRECEDING .. v FOLLOWING by value
                return f"range_bounded:{(1 << 62)}:{fl}"
            # UNBOUNDED PRECEDING .. f FOLLOWING: bounded with a huge
            # preceding span (partition sizes are < 2^31)
            return f"rows_bounded:{(1 << 31) - 1}:{fl}"
        if f.end.endswith("_preceding") and f.end[0].isdigit():
            raise AnalysisError(
                "frames ending before CURRENT ROW are unsupported")
        return "partition"            # UNBOUNDED FOLLOWING

    def plan_windows(self, node: L.PlanNode, calls: List[A.WindowFunc],
                     lower, scope: Scope):
        """Plan window calls over `node`: a pass-through pre-projection
        adding window inputs, then one WindowNode per distinct
        (PARTITION BY, ORDER BY) group (Trino merges compatible
        specifications into shared WindowNodes the same way —
        MergeAdjacentWindows / PushdownWindow rules).

        Returns (new_node, slots {call -> ir.Expr over new output},
        fields {call -> Field or None}).
        """
        base_n = len(node.output)
        pre_exprs = [ir.ColumnRef(i, dt, nm)
                     for i, (nm, dt) in enumerate(node.output)]
        pre_cols = list(node.output)

        def add_input(e: ir.Expr) -> int:
            if isinstance(e, ir.ColumnRef) and e.index < base_n:
                return e.index        # bare column: pass-through slot
            for i, prev in enumerate(pre_exprs[base_n:]):
                if prev == e:         # structural dedup merges window groups
                    return base_n + i
            pre_exprs.append(e)
            pre_cols.append((f"$win{len(pre_cols)}", e.dtype))
            return len(pre_cols) - 1

        def const_int(ast: A.Node, what: str) -> int:
            e = lower(ast)
            if not isinstance(e, ir.Literal) or not isinstance(
                    e.value, (int, np.integer)):
                raise AnalysisError(f"{what} must be an integer literal")
            return int(e.value)

        groups: Dict[tuple, list] = {}
        records: Dict[A.WindowFunc, dict] = {}
        fields: Dict[A.WindowFunc, Optional[Field]] = {}
        for call in calls:
            part = tuple(add_input(lower(p)) for p in call.partition_by)
            okeys = []
            for o in call.order_by:
                idx = add_input(lower(o.expr))
                nf = o.nulls_first if o.nulls_first is not None \
                    else not o.ascending
                okeys.append(L.SortKey(idx, o.ascending, nf))
            rec = {"part": part, "order": tuple(okeys)}
            name, frame = call.name, self.frame_mode(call)
            if frame.startswith(("rows_bounded", "range_bounded")) and \
                    name not in ("sum", "count", "avg"):
                raise AnalysisError(
                    f"bounded ROWS/RANGE frames support sum/count/avg "
                    f"(not {name})")
            if frame.startswith("range_bounded"):
                # value-offset frames need ONE numeric sort key whose
                # comparisons the kernel's binary search can run on
                # int64 lanes (WindowOperator's RANGE frame contract);
                # DECIMAL keys scale the bound to unscaled units
                if len(okeys) != 1:
                    raise AnalysisError(
                        "RANGE frames with numeric bounds require "
                        "exactly one ORDER BY key")
                kdt = pre_cols[okeys[0].index][1]
                if kdt.kind is TypeKind.DECIMAL:
                    _, p_s, f_s = frame.split(":")
                    mul = 10 ** kdt.scale
                    cap = 1 << 62
                    frame = (f"range_bounded:"
                             f"{min(int(p_s) * mul, cap)}:"
                             f"{min(int(f_s) * mul, cap)}")
                elif kdt.kind not in (TypeKind.BIGINT, TypeKind.INTEGER,
                                      TypeKind.DATE):
                    raise AnalysisError(
                        "RANGE frame bounds require an integer-valued "
                        f"ORDER BY key (got {kdt.kind.name})")
            fields[call] = None
            if name in ("row_number", "rank", "dense_rank"):
                rec["specs"] = [L.WinSpecNode(name, None, frame, 1, None,
                                              name, BIGINT)]
            elif name == "ntile":
                if len(call.args) != 1:
                    raise AnalysisError("ntile takes one argument")
                k = const_int(call.args[0], "ntile bucket count")
                if k <= 0:
                    raise AnalysisError("ntile buckets must be positive")
                rec["specs"] = [L.WinSpecNode(name, None, frame, k, None,
                                              name, BIGINT)]
            elif name in ("lead", "lag"):
                if not 1 <= len(call.args) <= 3:
                    raise AnalysisError(f"{name} takes 1-3 arguments")
                arg = lower(call.args[0])
                off = const_int(call.args[1], f"{name} offset") \
                    if len(call.args) > 1 else 1
                if off < 0:
                    raise AnalysisError(f"{name} offset must be >= 0")
                default = None
                if len(call.args) > 2:
                    d = lower(call.args[2])
                    if not isinstance(d, ir.Literal):
                        raise AnalysisError(
                            f"{name} default must be a literal")
                    # rescale to the argument's representation (a DECIMAL
                    # default literal carries its own scale)
                    default = _convert_const(d.value, d.dtype, arg.dtype)
                slot = add_input(arg)
                fields[call] = self.field_for(arg, scope)
                if arg.dtype.kind is TypeKind.VARCHAR and \
                        default is not None:
                    raise AnalysisError(
                        f"{name} varchar default unsupported")
                rec["specs"] = [L.WinSpecNode(name, slot, frame, off,
                                              default, name, arg.dtype)]
            elif name in ("first_value", "last_value"):
                if len(call.args) != 1:
                    raise AnalysisError(f"{name} takes one argument")
                arg = lower(call.args[0])
                slot = add_input(arg)
                fields[call] = self.field_for(arg, scope)
                rec["specs"] = [L.WinSpecNode(name, slot, frame, 1, None,
                                              name, arg.dtype)]
            elif name == "count" and (call.is_star or not call.args):
                rec["specs"] = [L.WinSpecNode("count_star", None, frame, 1,
                                              None, "count", BIGINT)]
            else:                     # sum/count/min/max/avg aggregates
                if len(call.args) != 1:
                    raise AnalysisError(f"{name} takes one argument")
                arg = lower(call.args[0])
                t = arg.dtype
                if t.kind is TypeKind.VARCHAR and name in ("min", "max"):
                    raise AnalysisError(
                        f"window {name}() over varchar unsupported")
                slot = add_input(arg)
                if name == "avg":
                    rec["specs"] = [
                        L.WinSpecNode("sum", slot, frame, 1, None,
                                      "avg_sum", sum_type(t)),
                        L.WinSpecNode("count", slot, frame, 1, None,
                                      "avg_cnt", BIGINT)]
                    rec["avg_t"] = t
                elif name == "sum":
                    rec["specs"] = [L.WinSpecNode("sum", slot, frame, 1,
                                                  None, "sum", sum_type(t))]
                elif name == "count":
                    rec["specs"] = [L.WinSpecNode("count", slot, frame, 1,
                                                  None, "count", BIGINT)]
                else:
                    rec["specs"] = [L.WinSpecNode(name, slot, frame, 1,
                                                  None, name, t)]
            records[call] = rec
            groups.setdefault((part, rec["order"]), []).append(call)

        current: L.PlanNode = L.ProjectNode(node, tuple(pre_exprs),
                                            tuple(pre_cols))
        slots: Dict[A.WindowFunc, ir.Expr] = {}
        for (part, okeys), group_calls in groups.items():
            specs = []
            first_out = len(current.output)
            for call in group_calls:
                rec = records[call]
                out0 = first_out + len(specs)
                specs.extend(rec["specs"])
                if "avg_t" in rec:
                    t = rec["avg_t"]
                    sum_ref = ir.ColumnRef(out0, sum_type(t))
                    cnt_ref = ir.ColumnRef(out0 + 1, BIGINT)
                    if t.kind is TypeKind.DECIMAL:
                        slots[call] = ir.DecimalAvg(sum_ref, cnt_ref, t)
                    else:
                        slots[call] = ir.arith(
                            "/", ir.Cast(sum_ref, DOUBLE),
                            ir.Cast(cnt_ref, DOUBLE))
                else:
                    slots[call] = ir.ColumnRef(out0,
                                               rec["specs"][0].out_dtype)
            output = tuple(current.output) + tuple(
                (s.out_name, s.out_dtype) for s in specs)
            current = L.WindowNode(current, part, okeys, tuple(specs),
                                   output)
        return current, slots, fields

    def field_for(self, e: ir.Expr, scope: Scope):
        """Propagate dictionary fields through bare column projections,
        and through CASE when every branch shares one pool."""
        if isinstance(e, ir.DerivedDict):
            return Field("$derived", e.dtype, dictionary=e.pool)
        if isinstance(e, ir.ArrayConst):
            return Field("$array", e.dtype, dictionary=e.pool)
        if isinstance(e, ir.Literal) and e.dtype is not None and \
                e.dtype.kind is TypeKind.VARCHAR:
            return Field("$literal", e.dtype, dictionary=(e.value,))
        if isinstance(e, ir.ColumnRef) and \
                e.dtype.kind in (TypeKind.VARCHAR, TypeKind.ARRAY):
            for c in scope.columns:
                if c.index == e.index and c.dtype.kind is e.dtype.kind:
                    return c.field
        if isinstance(e, ir.Case) and e.dtype.kind is TypeKind.VARCHAR:
            branches = [v for _, v in e.whens]
            if e.default is not None:
                branches.append(e.default)
            fields = [self.field_for(b, scope) for b in branches]
            pools = {f.dictionary for f in fields if f is not None}
            if len(fields) == len(branches) and len(pools) == 1 and \
                    all(f is not None for f in fields):
                return fields[0]
        return None

    # ---- aggregation ------------------------------------------------------

    def plan_aggregation(self, q: A.Query, rel: PlannedRelation):
        scope = rel.scope
        lowerer = ExpressionLowerer(scope)

        group_asts = list(q.group_by)
        group_irs = [lowerer.lower(resolve_ordinal(g, q)) for g in group_asts]

        # collect distinct aggregate calls across select/having/order
        agg_calls: List[A.FunctionCall] = []

        def collect(node: A.Node):
            if isinstance(node, A.FunctionCall) and node.name in AGG_NAMES:
                if node not in agg_calls:
                    agg_calls.append(node)
                return
            for ch in ast_children(node):
                collect(ch)

        for item in q.select:
            if item.expr is not None:
                collect(item.expr)
        if q.having is not None:
            collect(q.having)
        for o in q.order_by:
            collect(o.expr)

        # pre-projection: group keys then agg args
        pre_exprs: List[ir.Expr] = list(group_irs)
        pre_cols: List[Tuple[str, DataType]] = [
            (f"gk{i}", e.dtype) for i, e in enumerate(group_irs)]
        agg_specs: List[L.AggSpecNode] = []
        # map from agg call -> (post-agg expression builder)
        call_slots: Dict[A.FunctionCall, Tuple[str, int, int]] = {}

        def add_arg(e: ir.Expr) -> int:
            # reuse identical pre-projection expressions: DISTINCT
            # aggregates over the same argument must share one sort
            # column (count(DISTINCT x) + approx_distinct(x))
            for i, prev in enumerate(pre_exprs):
                if prev == e:
                    return i
            pre_exprs.append(e)
            pre_cols.append((f"a{len(pre_exprs)}", e.dtype))
            return len(pre_exprs) - 1

        n_keys = len(group_irs)
        distinct_args: List[int] = []
        # approx_distinct -> HLL relational rewrite (below): each entry
        # is (call, bucket_slot, rho_slot). Grouping sets keep the exact
        # sort-distinct lowering (the rewrite would have to replicate
        # per grouping set).
        hll_calls: List[tuple] = []
        dsum_types: Dict[A.FunctionCall, DataType] = {}
        # a DISTINCT sum/count shares the sort kernel's dedup column; the
        # HLL rewrite can't carry it through the (keys, bucket) inner
        # grouping, so approx_distinct degrades to exact sort-distinct
        # whenever one is present
        any_exact_distinct = any(
            c.distinct and c.name in ("sum", "count") for c in agg_calls)
        for call in agg_calls:
            if call.distinct and call.name == "avg":
                raise AnalysisError("avg(DISTINCT) not yet supported")
            if call.is_star or (call.name == "count" and not call.args):
                agg_specs.append(L.AggSpecNode("count_star", None,
                                               "count", BIGINT))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
                continue
            if len(call.args) != 1:
                raise AnalysisError(f"{call.name} takes one argument")
            arg = lowerer.lower(call.args[0])
            if call.name == "approx_distinct" and not q.grouping_sets \
                    and not any_exact_distinct:
                b_slot = add_arg(ir.ScalarFunc(
                    "$hll_bucket", (arg,), BIGINT, (HLL_P,)))
                r_slot = add_arg(ir.ScalarFunc(
                    "$hll_rho", (arg,), BIGINT, (HLL_P,)))
                hll_calls.append((call, b_slot, r_slot))
                continue
            slot = add_arg(arg)
            t = arg.dtype
            # min/max DISTINCT == plain min/max; sum/count DISTINCT need
            # the sort kernel's duplicate-elimination (one distinct column
            # per aggregation, enforced below)
            distinct = (call.distinct and call.name in ("sum", "count")) \
                or call.name == "approx_distinct"
            if distinct:
                distinct_args.append(slot)
                if len(set(distinct_args)) > 1:
                    raise AnalysisError(
                        "multiple DISTINCT aggregate arguments unsupported")
            if call.name in ("count", "approx_distinct"):
                agg_specs.append(L.AggSpecNode("count", ir.ColumnRef(
                    slot, t), "count", BIGINT, distinct))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
            elif call.name in ("bool_and", "bool_or", "every"):
                if t.kind is not TypeKind.BOOLEAN:
                    raise AnalysisError(f"{call.name} requires a boolean")
                # AND == min over {0,1}; OR == max (BooleanAndAggregation)
                b_slot = add_arg(ir.Cast(arg, BIGINT))
                fn = "max" if call.name == "bool_or" else "min"
                agg_specs.append(L.AggSpecNode(
                    fn, ir.ColumnRef(b_slot, BIGINT), call.name, BIGINT))
                call_slots[call] = ("bool", len(agg_specs) - 1, -1)
            elif call.name in ("min", "max"):
                agg_specs.append(L.AggSpecNode(call.name, ir.ColumnRef(
                    slot, t), call.name, t))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
            elif call.name == "sum":
                out_t = sum_type(t)
                if t.kind is TypeKind.DECIMAL and not distinct and \
                        not q.grouping_sets:
                    # two-limb accumulation (see ops/project.py
                    # $limb_hi): the states are plain int64 sums, so
                    # chunked/distributed merging needs no new machinery
                    hi_slot = add_arg(ir.ScalarFunc(
                        "$limb_hi", (arg,), BIGINT))
                    lo_slot = add_arg(ir.ScalarFunc(
                        "$limb_lo", (arg,), BIGINT))
                    agg_specs.append(L.AggSpecNode(
                        "sum", ir.ColumnRef(hi_slot, BIGINT), "$dshi",
                        BIGINT))
                    agg_specs.append(L.AggSpecNode(
                        "sum", ir.ColumnRef(lo_slot, BIGINT), "$dslo",
                        BIGINT))
                    call_slots[call] = ("dsum", len(agg_specs) - 2,
                                        len(agg_specs) - 1)
                    dsum_types[call] = out_t
                    continue
                agg_specs.append(L.AggSpecNode("sum", ir.ColumnRef(slot, t),
                                               "sum", out_t, distinct))
                call_slots[call] = ("plain", len(agg_specs) - 1, -1)
            elif call.name == "avg":
                out_t = t if t.kind is TypeKind.DECIMAL else DOUBLE
                agg_specs.append(L.AggSpecNode("sum", ir.ColumnRef(slot, t),
                                               "avg_sum", sum_type(t)))
                agg_specs.append(L.AggSpecNode("count", ir.ColumnRef(
                    slot, t), "avg_cnt", BIGINT))
                call_slots[call] = ("avg", len(agg_specs) - 2,
                                    len(agg_specs) - 1)
            elif call.name in VARIANCE_AGGS:
                # decompose to (sum x², sum x, count x) in DOUBLE; the
                # finalizer divides/sqrt's post-aggregation (Trino's
                # VarianceState accumulators)
                x = ir.Cast(arg, DOUBLE) \
                    if t.kind is not TypeKind.DOUBLE else arg
                x_slot = add_arg(x)
                sq_slot = add_arg(ir.arith("*", x, x))
                agg_specs.append(L.AggSpecNode(
                    "sum", ir.ColumnRef(sq_slot, DOUBLE), "var_sq",
                    DOUBLE))
                agg_specs.append(L.AggSpecNode(
                    "sum", ir.ColumnRef(x_slot, DOUBLE), "var_sum",
                    DOUBLE))
                agg_specs.append(L.AggSpecNode(
                    "count", ir.ColumnRef(x_slot, DOUBLE), "var_cnt",
                    BIGINT))
                call_slots[call] = ("var", len(agg_specs) - 3,
                                    len(agg_specs) - 2)

        pre_node = L.ProjectNode(rel.node, tuple(pre_exprs),
                                 tuple(pre_cols))

        # grouping() calls (sql/analyzer's GroupingOperationRewriter role):
        # each call's value is branch-static per grouping set, so the
        # grouping-sets planner appends one literal column per call
        grouping_calls: List[A.FunctionCall] = []
        for item in q.select:
            if item.expr is not None:
                collect_grouping_calls(item.expr, grouping_calls)
        if q.having is not None:
            collect_grouping_calls(q.having, grouping_calls)
        for ob in q.order_by:
            collect_grouping_calls(ob.expr, grouping_calls)
        grouping_specs = []
        for call in grouping_calls:
            idxs = []
            for a in call.args:
                for i, g_ast in enumerate(group_asts):
                    if ast_equal(a, g_ast, q):
                        idxs.append(i)
                        break
                else:
                    raise AnalysisError(
                        "grouping() arguments must be grouping keys")
            grouping_specs.append(tuple(idxs))

        agg_out = tuple(
            [(f"gk{i}", e.dtype) for i, e in enumerate(group_irs)] +
            [(s.out_name, s.out_dtype) for s in agg_specs] +
            ([(f"$grouping{i}", BIGINT)
              for i in range(len(grouping_specs))]
             if q.grouping_sets else []))
        if q.grouping_sets:
            agg_node = self.plan_grouping_sets(
                q.grouping_sets, pre_node, group_irs, agg_specs, scope,
                agg_out, bool(distinct_args),
                grouping_specs=tuple(grouping_specs))
        elif hll_calls:
            agg_node, agg_specs = self.plan_hll_aggregation(
                q, pre_node, group_irs, agg_specs, scope, hll_calls,
                call_slots, distinct_args)
            agg_out = tuple(
                [(f"gk{i}", e.dtype) for i, e in enumerate(group_irs)] +
                [(s.out_name, s.out_dtype) for s in agg_specs])
        else:
            strategy, domains, capacity = self.agg_strategy(
                group_irs, scope, pre_node,
                any_distinct=bool(distinct_args))
            agg_node = L.AggregateNode(
                pre_node, tuple(range(n_keys)), tuple(agg_specs),
                strategy, domains, capacity, agg_out)

        # post-projection scope: group keys (referencing original key ASTs)
        # then aggregate slots
        post_scope_cols = []
        for i, (g_ast, g_ir) in enumerate(zip(group_asts, group_irs)):
            fld = self.field_for(g_ir, scope)
            post_scope_cols.append(ScopeColumn(None, f"gk{i}", g_ir.dtype,
                                               i, fld))
        post_scope = Scope(post_scope_cols)

        window_slots: Dict[A.WindowFunc, ir.Expr] = {}
        planner_self = self

        class _PostAggLowerer(ExpressionLowerer):
            """Lowers select/having/order expressions over the aggregation
            output: group-key ASTs match syntactically (like Trino),
            aggregate calls resolve to their output slots, everything else
            (BETWEEN, IN, CASE, scalar functions, subqueries, ...) falls
            through to the full expression lowerer against the post-agg
            scope."""

            def lower(inner, node: A.Node) -> ir.Expr:
                for i, g_ast in enumerate(group_asts):
                    if ast_equal(node, g_ast, q):
                        c = post_scope.columns[i]
                        return ir.ColumnRef(c.index, c.dtype, c.name)
                if isinstance(node, A.FunctionCall) and \
                        node.name == "grouping":
                    if not q.grouping_sets:
                        return ir.Literal(0, BIGINT)
                    for gi, gcall in enumerate(grouping_calls):
                        if gcall is node or ast_equal(node, gcall, q):
                            return ir.ColumnRef(
                                n_keys + len(agg_specs) + gi, BIGINT)
                    raise AnalysisError("grouping() call not analyzed")
                if isinstance(node, A.FunctionCall) and \
                        node.name in AGG_NAMES:
                    kind, s1, s2 = call_slots[node]
                    if kind == "plain":
                        spec = agg_specs[s1]
                        return ir.ColumnRef(n_keys + s1, spec.out_dtype)
                    if kind == "hll":
                        # finisher over (V = occupied registers,
                        # S = sum 2^-rho) — see plan_hll_aggregation
                        from ..types import DOUBLE as _D
                        return ir.ScalarFunc(
                            "$hll_est",
                            (ir.ColumnRef(n_keys + s1, BIGINT),
                             ir.ColumnRef(n_keys + s2, _D)),
                            BIGINT, (1 << HLL_P,))
                    if kind == "dsum":
                        # two-limb decimal sum combine: hi*2^32 + lo on
                        # RAW unscaled ints (Arith's decimal coercions
                        # must not rescale limbs), exact while
                        # |total| < 2^63 (Int128State's role)
                        hi = ir.ColumnRef(n_keys + s1, BIGINT)
                        lo = ir.ColumnRef(n_keys + s2, BIGINT)
                        return ir.ScalarFunc(
                            "$limb_combine", (hi, lo), dsum_types[node])
                    if kind == "bool":
                        return ir.Compare(
                            "=", ir.ColumnRef(n_keys + s1, BIGINT),
                            ir.Literal(1, BIGINT))
                    if kind == "var":
                        # finalize variance family from (Σx², Σx, n):
                        # m2 = Σx² - (Σx)²/n; var_pop = m2/n,
                        # var_samp = m2/(n-1); n-1 = 0 divides to NULL
                        sq = ir.ColumnRef(n_keys + s1, DOUBLE)
                        sm = ir.ColumnRef(n_keys + s2, DOUBLE)
                        n_ref = ir.Cast(ir.ColumnRef(n_keys + s2 + 1,
                                                     BIGINT), DOUBLE)
                        m2_raw = ir.arith("-", sq, ir.arith(
                            "/", ir.arith("*", sm, sm), n_ref))
                        # clamp tiny negative fp residue so sqrt stays
                        # defined (Trino's accumulators never go negative)
                        zero = ir.Literal(0.0, DOUBLE)
                        m2 = ir.Case(
                            ((ir.Compare('<', m2_raw, zero), zero),),
                            m2_raw, DOUBLE)
                        name = node.name
                        if name in ("variance", "var_samp", "stddev",
                                    "stddev_samp"):
                            denom = ir.arith("-", n_ref,
                                             ir.Literal(1.0, DOUBLE))
                        else:
                            denom = n_ref
                        var = ir.arith("/", m2, denom)
                        if name.startswith("stddev"):
                            return ir.ScalarFunc("sqrt", (var,), DOUBLE)
                        return var
                    sum_ref = ir.ColumnRef(n_keys + s1,
                                           agg_specs[s1].out_dtype)
                    cnt_ref = ir.ColumnRef(n_keys + s2, BIGINT)
                    arg_t = agg_specs[s1].arg.dtype
                    if arg_t.kind is TypeKind.DECIMAL:
                        return ir.DecimalAvg(sum_ref, cnt_ref, arg_t)
                    return ir.arith("/", ir.Cast(sum_ref, DOUBLE),
                                    ir.Cast(cnt_ref, DOUBLE))
                if isinstance(node, A.Identifier):
                    col = post_scope.try_resolve(node.parts)
                    if col is None:
                        raise AnalysisError(
                            f"column {'.'.join(node.parts)} must appear "
                            f"in GROUP BY")
                return super().lower(node)

        rewrite = _PostAggLowerer(post_scope, planner=planner_self,
                                  window_slots=window_slots).lower

        items = []
        for item in q.select:
            if item.expr is None:
                raise AnalysisError("* not allowed with GROUP BY")
            name = (item.alias or default_name(item.expr)).lower()
            items.append((item.expr, name))

        current: L.PlanNode = agg_node
        if q.having is not None:
            pred = rewrite(q.having)
            current = L.FilterNode(current, pred, current.output)

        # windows over the aggregated output (sum(sum(x)) OVER (...) etc.);
        # ORDER BY windows must match a select item (there is no hidden-
        # sort-column path through aggregation), so only items are scanned
        wcalls: List[A.WindowFunc] = []
        for ast, _ in items:
            self.collect_windows(ast, wcalls)
        wfields: Dict[A.WindowFunc, Optional[Field]] = {}
        if wcalls:
            current, slots, wfields = self.plan_windows(
                current, wcalls, rewrite, post_scope)
            window_slots.update(slots)

        post_exprs = []
        names = []
        out_cols = []
        final_scope = []
        for i, (ast, name) in enumerate(items):
            e = materialize_string(rewrite(ast))
            post_exprs.append(e)
            names.append(name)
            out_cols.append((name, e.dtype))
            fld = None
            if isinstance(e, ir.ColumnRef) and e.index < n_keys:
                fld = post_scope.columns[e.index].field
            if fld is None and isinstance(ast, A.WindowFunc):
                fld = wfields.get(ast)
            if fld is None:
                # literal tags ('s' AS sale_type) and derived dictionary
                # expressions keep their pools through aggregation
                fld = self.field_for(e, post_scope)
            final_scope.append(ScopeColumn(None, name, e.dtype, i, fld))

        post_node = L.ProjectNode(current, tuple(post_exprs),
                                  tuple(out_cols))
        # ORDER BY may reference aggregation-scope expressions not in the
        # select list (e.g. CASE over grouping() keys); keep the rewrite
        # closure so the caller can lower them as hidden sort columns
        self._post_agg = (post_node, rewrite)
        return (PlannedRelation(post_node, Scope(final_scope)),
                post_exprs, names)

    def plan_grouping_sets(self, sets, pre_node, group_irs, agg_specs,
                           scope, agg_out, any_distinct,
                           grouping_specs=()) -> L.PlanNode:
        """ROLLUP/CUBE/GROUPING SETS: one aggregation per set over the
        shared pre-projection, aligned to the full key layout with NULL
        padding, concatenated with UNION ALL (the role of Trino's
        GroupIdOperator + single pass, expressed set-at-a-time — each
        branch still runs as one fused device program)."""
        n_keys = len(group_irs)
        branches = []
        for set_idxs in sets:
            set_idxs = tuple(set_idxs)
            sub_irs = [group_irs[i] for i in set_idxs]
            strategy, domains, capacity = self.agg_strategy(
                sub_irs, scope, pre_node, any_distinct=any_distinct)
            sub_out = tuple(
                [(f"gk{i}", group_irs[i].dtype) for i in set_idxs] +
                [(s.out_name, s.out_dtype) for s in agg_specs])
            node = L.AggregateNode(pre_node, set_idxs, tuple(agg_specs),
                                   strategy, domains, capacity, sub_out)
            # align to the full (gk0..gkN, aggs) layout with NULL keys
            pos = {k: j for j, k in enumerate(set_idxs)}
            exprs = []
            for i, g in enumerate(group_irs):
                if i in pos:
                    exprs.append(ir.ColumnRef(pos[i], g.dtype))
                else:
                    exprs.append(ir.Literal(None, g.dtype))
            for j, s in enumerate(agg_specs):
                exprs.append(ir.ColumnRef(len(set_idxs) + j, s.out_dtype))
            # grouping() literals: bit j set = the call's j-th argument is
            # aggregated away in this set (spi semantics of grouping())
            in_set = set(set_idxs)
            for arg_idxs in grouping_specs:
                v = 0
                for j, gi in enumerate(arg_idxs):
                    if gi not in in_set:
                        v |= 1 << (len(arg_idxs) - 1 - j)
                exprs.append(ir.Literal(v, BIGINT))
            branches.append(L.ProjectNode(node, tuple(exprs), agg_out))
        current = branches[0]
        none_maps = (None,) * len(agg_out)
        for b in branches[1:]:
            current = L.SetOpNode("union_all", current, b, none_maps,
                                  none_maps, agg_out)
        return current

    def plan_hll_aggregation(self, q, pre_node, group_irs, agg_specs,
                             scope, hll_calls, call_slots, distinct_args):
        """approx_distinct as a relational HLL rewrite (the TPU answer to
        ApproximateCountDistinctAggregation.java's per-group sketch
        objects):

            inner : GROUP BY keys + $hll_bucket(x) -> max($hll_rho(x)),
                    other aggregates as mergeable partials
            mid   : project 2^-max_rho
            outer : GROUP BY keys -> merge partials,
                    V = count(max_rho), S = sum(2^-max_rho)
            post  : $hll_est(V, S) finisher expression

        The inner aggregate is max/sum/count only, so the chunked driver
        and the distributed source stage merge its partial states with
        the ordinary machinery — bounded 2^p rows of state per group,
        where the exact sort-distinct path has unbounded state."""
        from ..types import DOUBLE as _D
        assert not distinct_args, \
            "caller routes DISTINCT mixes to the exact path"
        uniq = {}
        for call, b, r in hll_calls:
            uniq.setdefault((b, r), []).append(call)
        if len(uniq) > 1:
            raise AnalysisError(
                "multiple approx_distinct arguments unsupported")
        (b_slot, r_slot), calls = next(iter(uniq.items()))
        n_keys = len(group_irs)
        npart = len(agg_specs)

        # inner aggregate: keys + bucket, partial states + max(rho)
        inner_specs = list(agg_specs) + [L.AggSpecNode(
            "max", ir.ColumnRef(r_slot, BIGINT), "$mrho", BIGINT)]
        inner_out = tuple(
            [(f"gk{i}", e.dtype) for i, e in enumerate(group_irs)] +
            [("$hllb", BIGINT)] +
            [(s.out_name, s.out_dtype) for s in inner_specs])
        # capacity: per-group state saturates at 2^p registers, and the
        # total can never exceed the input row count
        base = self._sort_capacity(group_irs, scope, pre_node) \
            if group_irs else 1
        rows = max(1024, self.estimate_rows(pre_node))
        cap = min(max(base, 1) * (1 << HLL_P), rows)
        cap = 1 << (int(cap) - 1).bit_length()
        inner = L.AggregateNode(
            pre_node, tuple(range(n_keys)) + (b_slot,),
            tuple(inner_specs), "sort", (), cap, inner_out)

        # mid projection: pass keys + partials, add 2^-max_rho
        mrho = ir.ColumnRef(n_keys + 1 + npart, BIGINT)
        mid_exprs = tuple(
            [ir.ColumnRef(i, group_irs[i].dtype) for i in range(n_keys)] +
            [ir.ColumnRef(n_keys + 1 + j, s.out_dtype)
             for j, s in enumerate(agg_specs)] +
            [mrho, ir.ScalarFunc("$hll_pow", (mrho,), _D)])
        mid_out = tuple(
            [(f"gk{i}", e.dtype) for i, e in enumerate(group_irs)] +
            [(s.out_name, s.out_dtype) for s in agg_specs] +
            [("$mrho", BIGINT), ("$hpow", _D)])
        mid = L.ProjectNode(inner, mid_exprs, mid_out)

        # outer aggregate: merge partials, count/sum the register rows —
        # the same merge vocabulary the chunked driver uses, shared so
        # the two can't drift
        from ..exec.chunked import MERGE_FUNC as merge_of
        outer_specs = [
            L.AggSpecNode(merge_of[s.func],
                          ir.ColumnRef(n_keys + j, s.out_dtype),
                          s.out_name, s.out_dtype)
            for j, s in enumerate(agg_specs)]
        outer_specs.append(L.AggSpecNode(
            "count", ir.ColumnRef(n_keys + npart, BIGINT),
            "$hllv", BIGINT))
        outer_specs.append(L.AggSpecNode(
            "sum", ir.ColumnRef(n_keys + npart + 1, _D), "$hlls", _D))
        agg_out = tuple(
            [(f"gk{i}", e.dtype) for i, e in enumerate(group_irs)] +
            [(s.out_name, s.out_dtype) for s in outer_specs])
        strategy, domains, capacity = self.agg_strategy(
            group_irs, scope, pre_node)
        outer = L.AggregateNode(
            mid, tuple(range(n_keys)), tuple(outer_specs),
            strategy, domains, capacity, agg_out)
        for call in calls:
            call_slots[call] = ("hll", npart, npart + 1)
        return outer, list(outer_specs)

    def agg_strategy(self, group_irs, scope: Scope, pre_node,
                     any_distinct: bool = False):
        if not group_irs:
            # global DISTINCT aggregates run the sort kernel with zero
            # group keys (one segment); the executor falls back to
            # global_aggregate on empty input so the mandatory single
            # output row survives
            if any_distinct:
                return "sort", (), 1
            return "global", (), 0
        if any_distinct:
            return "sort", (), DEFAULT_SORT_GROUPS   # needs the sort kernel
        hmode = str(self.properties.get("hash_agg_mode", "auto")).lower()
        if hmode == "force":
            # ops/test knob: route every grouped aggregate through the
            # hash kernel (DISTINCT stays on sort — kernel contract)
            return "hash", (), self._sort_capacity(group_irs, scope,
                                                   pre_node)
        domains = []
        for e in group_irs:
            d = self.domain_of(e, scope)
            if d is None:
                domains = None
                break
            domains.append(d)
        if domains is not None:
            prod = math.prod(domains)
            # stats-driven cutoff (GroupByHash.java:82-93's role): the
            # direct strategy is a G-pass masked-reduction graph whose
            # compile time AND runtime scale with G, so it only pays
            # when groups are dense — many rows per group. The bound is
            # session-tunable; estimated rows-per-group below 64 fall to
            # the sort kernel (its cost is shape-, not G-, bound).
            limit = int(self.properties.get("direct_agg_max_groups",
                                            MAX_DIRECT_GROUPS))
            limit = min(limit, MAX_DIRECT_GROUPS)
            est = self._input_rows_estimate(pre_node)
            if prod <= limit and (est is None or est >= prod * 64):
                return "direct", tuple(domains), prod
        capacity = self._sort_capacity(group_irs, scope, pre_node)
        # hash vs sort: the rows-per-group gate ("Hash-Based vs.
        # Sort-Based Group-By-Aggregate" — hash wins at HIGH cardinality,
        # i.e. FEW rows per group, where the sort pays O(n log n) to
        # discover mostly-distinct keys while the VMEM hash table pays
        # one insert per row). The executor still falls back to sort at
        # runtime when the kernel is off or the keys cannot pack.
        if hmode not in ("off", "false", "0"):
            est_groups, rows = self._group_rows_estimate(
                group_irs, scope, pre_node)
            min_groups = int(self.properties.get(
                "hash_agg_min_groups", 8192))
            max_rpg = float(self.properties.get(
                "hash_agg_max_rows_per_group", 64))
            if est_groups is not None and rows is not None and \
                    est_groups >= min_groups and \
                    rows <= est_groups * max_rpg:
                return "hash", (), capacity
        return "sort", (), capacity

    def _input_rows_estimate(self, pre_node) -> Optional[int]:
        """Rough input-row bound for strategy choice: the largest scan
        under the aggregate's input chain (filters only shrink it)."""
        node = pre_node
        while isinstance(node, (L.FilterNode, L.ProjectNode)):
            node = node.child
        from .fragmenter import _subtree_nodes
        scans = [n for n in _subtree_nodes(node)
                 if isinstance(n, L.ScanNode)]
        if not scans:
            return None
        try:
            return max(self.catalog.get_table(
                s.catalog, s.schema_name, s.table).num_rows
                for s in scans)
        except Exception:      # noqa: BLE001 — stats are best-effort
            return None

    def _group_rows_estimate(self, group_irs, scope: Scope, pre_node):
        """(estimated group count, estimated input rows) from column
        NDV stats — the shared input of the sort-capacity sizing and
        the hash-vs-sort rows-per-group gate. (None, None) without
        stats."""
        cstats = self.chain_column_stats(pre_node.child) \
            if isinstance(pre_node, L.ProjectNode) else None
        if cstats is None:
            return None, None
        # group keys are the pre-projection's leading exprs
        prod = 1.0
        for e in group_irs:
            s = cstats.get(e.index) if isinstance(e, ir.ColumnRef) \
                else None
            if s is None:
                return None, None
            prod *= max(1.0, s.ndv)
        rows = self.estimate_rows(pre_node.child)
        return min(prod, rows), rows

    def _sort_capacity(self, group_irs, scope: Scope, pre_node) -> int:
        """Size the sort-aggregation output from stats (NDV product capped
        by input rows) instead of a fixed default: every capacity retry is
        a fresh XLA compile plus a full re-sort, so landing right the
        first time is the difference between one device pass and four
        (GroupByHash's expectedSize estimation)."""
        est, _rows = self._group_rows_estimate(group_irs, scope,
                                               pre_node)
        if est is None:
            return DEFAULT_SORT_GROUPS
        # 1.3x headroom, pow2 bucket (stable jit cache), floor at the
        # default so small queries share one trace
        cap = 1 << max(1, int(1.3 * est) - 1).bit_length()
        return int(min(max(cap, DEFAULT_SORT_GROUPS), 1 << 26))

    def domain_of(self, e: ir.Expr, scope: Scope) -> Optional[int]:
        if isinstance(e, ir.DerivedDict):
            return len(e.pool)
        if isinstance(e, ir.ColumnRef):
            if e.dtype.kind is TypeKind.VARCHAR:
                for c in scope.columns:
                    if c.index == e.index and c.field is not None and \
                            c.field.dictionary is not None:
                        return len(c.field.dictionary)
            if e.dtype.kind is TypeKind.BOOLEAN:
                return 2
        return None


    # ------------------------------------------------------------------
    # subquery predicates -> joins (decorrelation)
    # ------------------------------------------------------------------

    def plan_subquery_conjunct(self, rel: PlannedRelation,
                               c: A.Node) -> Optional[PlannedRelation]:
        """Try to absorb one unplaced conjunct that contains a subquery.
        Returns the rewritten relation, or None if this conjunct is not a
        supported subquery shape."""
        if isinstance(c, A.ExistsPredicate):
            return self.plan_exists(rel, c.query, c.negated)
        if isinstance(c, A.UnaryOp) and c.op == "not" and \
                isinstance(c.arg, A.ExistsPredicate):
            return self.plan_exists(rel, c.arg.query, not c.arg.negated)
        if isinstance(c, A.InSubquery):
            return self.plan_in_subquery(rel, c)
        if isinstance(c, A.BinaryOp) and c.op in ("=", "<>", "<", "<=",
                                                  ">", ">="):
            # the scalar subquery may sit anywhere in the comparison
            # (e.g. price > 1.2 * (SELECT avg ...)); decorrelate it and
            # re-lower the whole predicate with the subquery's value
            # column spliced in
            subs: List[A.ScalarSubquery] = []
            collect_scalar_subqueries(c, subs)
            if len(subs) == 1:
                return self.plan_correlated_scalar(rel, c, subs[0])
        if isinstance(c, A.BinaryOp) and c.op == "or":
            return self.plan_disjunctive_exists(rel, c)
        return None

    def plan_disjunctive_exists(self, rel: PlannedRelation,
                                c: A.Node) -> Optional[PlannedRelation]:
        """(EXISTS s1 OR EXISTS s2 OR plain-pred ...) -> mark joins.

        Each EXISTS term becomes a mark join appending a hidden boolean
        column (TransformExistsApplyToCorrelatedJoin's MARK variant,
        operator-level JoinNode.Type.MARK in the reference); the disjunct
        then filters on the marks. EXISTS truth is 2-valued, so NOT
        EXISTS inside OR is a plain negation of its mark."""
        terms: List[A.Node] = []

        def flatten(node):
            if isinstance(node, A.BinaryOp) and node.op == "or":
                flatten(node.left)
                flatten(node.right)
            else:
                terms.append(node)
        flatten(c)

        def as_exists(t):
            if isinstance(t, A.ExistsPredicate):
                return t.query, t.negated
            if isinstance(t, A.UnaryOp) and t.op == "not" and \
                    isinstance(t.arg, A.ExistsPredicate):
                return t.arg.query, not t.arg.negated
            return None

        def has_subquery(node) -> bool:
            if isinstance(node, (A.ExistsPredicate, A.InSubquery,
                                 A.ScalarSubquery)):
                return True
            return any(has_subquery(ch) for ch in ast_children(node))

        exists_terms = [as_exists(t) for t in terms]
        if not any(e is not None for e in exists_terms):
            return None
        if any(e is None and has_subquery(t)
               for t, e in zip(terms, exists_terms)):
            return None          # OR mixing other subquery shapes: punt

        current = rel
        parts: List[ir.Expr] = []
        for t, e in zip(terms, exists_terms):
            if e is None:
                lowerer = ExpressionLowerer(current.scope, planner=self)
                parts.append(lowerer.to_bool(lowerer.lower(t)))
                continue
            subq, negated = e
            inner, corr, residual_asts = self.plan_inner_with_correlation(
                current, subq)
            if not corr:
                return None
            residual = None
            if residual_asts:
                lw = ExpressionLowerer(self.pair_scope(current, inner),
                                       planner=self)
                preds = [lw.to_bool(lw.lower(x)) for x in residual_asts]
                residual = preds[0] if len(preds) == 1 else ir.Logical(
                    "and", tuple(preds))
            node = self.make_join(
                "mark", current.node, inner.node,
                tuple(o for o, _ in corr),
                tuple(cc.index for _, cc in corr), residual, False,
                probe_fields=[self._scope_field(current.scope, o)
                              for o, _ in corr],
                build_fields=[cc.field for _, cc in corr])
            mark = ir.ColumnRef(len(node.output) - 1, BOOLEAN)
            parts.append(ir.Not(mark, BOOLEAN) if negated else mark)
            current = PlannedRelation(node, current.scope)
        pred = parts[0] if len(parts) == 1 else ir.Logical(
            "or", tuple(parts))
        out = L.FilterNode(current.node, pred, current.node.output)
        return PlannedRelation(out, rel.scope)

    def plan_inner_with_correlation(self, outer: PlannedRelation,
                                    subq: A.Query):
        """Plan a subquery's FROM/WHERE, separating correlation.

        Returns (inner_rel, corr_pairs, residual_asts):
        - corr_pairs: [(outer_col_index, inner_col_index)] from equi
          conjuncts linking the scopes (the future join keys);
        - residual_asts: leftover conjuncts referencing both scopes
          (lowered later over the concatenated probe++build scope).
        Inner-only conjuncts are already pushed into inner_rel."""
        if subq.group_by or subq.having or subq.ctes:
            raise AnalysisError(
                "correlated subquery with GROUP BY/HAVING unsupported")
        inner_rels, on_conj = self.plan_relation_tree(subq.relation)
        conjuncts: List[A.Node] = list(on_conj)
        if subq.where is not None:
            split_conjuncts(subq.where, conjuncts)
        add_or_common_conjuncts(conjuncts)
        inner = self.combine_relations(inner_rels, conjuncts)
        inner = self.apply_local_filters(inner, conjuncts)
        corr: List[Tuple[int, ScopeColumn]] = []
        residual: List[A.Node] = []
        for c in list(conjuncts):
            eq = as_equi(c)
            if eq is not None:
                a, b = eq
                oa = outer.scope.try_resolve(a)
                ib = inner.scope.try_resolve(b)
                if oa is not None and ib is not None:
                    corr.append((oa.index, ib))
                    conjuncts.remove(c)
                    continue
                ob = outer.scope.try_resolve(b)
                ia = inner.scope.try_resolve(a)
                if ob is not None and ia is not None:
                    corr.append((ob.index, ia))
                    conjuncts.remove(c)
                    continue
            residual.append(c)
            conjuncts.remove(c)
        return inner, corr, residual

    def pair_scope(self, outer: PlannedRelation,
                   inner: PlannedRelation) -> Scope:
        """Concatenated probe++build scope for join residual lowering."""
        n = len(outer.node.output)
        cols = list(outer.scope.columns) + [
            ScopeColumn(c.qualifier, c.name, c.dtype, c.index + n, c.field)
            for c in inner.scope.columns]
        return Scope(cols)

    def plan_exists(self, outer: PlannedRelation, subq: A.Query,
                    negated: bool) -> PlannedRelation:
        """[NOT] EXISTS (correlated) -> semi/anti join
        (TransformCorrelatedExistsToJoin's role). Non-equi correlated
        conjuncts become the join residual (mark-join kernel)."""
        inner, corr, residual_asts = self.plan_inner_with_correlation(
            outer, subq)
        if not corr:
            raise AnalysisError("uncorrelated EXISTS not supported")
        residual = None
        if residual_asts:
            lowerer = ExpressionLowerer(self.pair_scope(outer, inner),
                                        planner=self)
            preds = [lowerer.to_bool(lowerer.lower(x))
                     for x in residual_asts]
            residual = preds[0] if len(preds) == 1 else ir.Logical(
                "and", tuple(preds))
        node = self.make_join(
            "anti" if negated else "semi", outer.node, inner.node,
            tuple(o for o, _ in corr), tuple(c.index for _, c in corr),
            residual, False,
            probe_fields=[self._scope_field(outer.scope, o)
                          for o, _ in corr],
            build_fields=[c.field for _, c in corr])
        return PlannedRelation(node, outer.scope)

    def plan_in_subquery(self, outer: PlannedRelation,
                         c: A.InSubquery) -> PlannedRelation:
        """x [NOT] IN (subquery) -> semi/anti join on x = subquery output.
        NOT IN is null-aware: NULL x never passes (pre-filter), and any
        NULL in the subquery output empties the result (executor check) —
        SQL three-valued NOT IN semantics."""
        sub = self.plan_query(c.query)
        if len(sub.scope.columns) != 1:
            raise AnalysisError("IN subquery must return one column")
        build_node = sub.node.child if isinstance(sub.node, L.OutputNode) \
            else sub.node

        lowerer = ExpressionLowerer(outer.scope, planner=self)
        key = lowerer.lower(c.arg)
        probe = outer
        # capture the key's dictionary BEFORE any probe extension: a
        # computed key's field is derivable only from the expression
        key_field = self.field_for(key, outer.scope)
        if not isinstance(key, ir.ColumnRef):
            # extend the probe with a computed key column (hidden)
            exprs = [ir.ColumnRef(i, t, n) for i, (n, t)
                     in enumerate(outer.node.output)] + [key]
            out = tuple(outer.node.output) + ((f"$inkey", key.dtype),)
            probe = PlannedRelation(
                L.ProjectNode(outer.node, tuple(exprs), out), outer.scope)
            key = ir.ColumnRef(len(out) - 1, key.dtype)
        if c.negated:
            # NULL probe keys can never satisfy NOT IN
            probe = PlannedRelation(
                L.FilterNode(probe.node, ir.IsNull(key, negated=True),
                             probe.node.output), probe.scope)
        node = self.make_join(
            "anti" if c.negated else "semi", probe.node, build_node,
            (key.index,), (0,), None, False,
            probe_fields=[key_field],
            build_fields=[sub.scope.columns[0].field],
            null_aware=c.negated)
        return PlannedRelation(node, outer.scope)

    def plan_correlated_scalar(self, outer: PlannedRelation,
                               conjunct: A.Node,
                               sub: A.ScalarSubquery) -> PlannedRelation:
        """Predicate containing (SELECT agg(...) FROM ... WHERE corr) ->
        group the subquery by its correlation keys, join, re-lower the
        whole predicate over outer ++ value column.
        (TransformCorrelatedScalarSubquery + aggregation decorrelation.)"""
        subq = sub.query
        if len(subq.select) != 1 or subq.select[0].expr is None:
            raise AnalysisError("scalar subquery must select one expression")
        if not contains_aggregate(subq.select[0].expr):
            raise AnalysisError(
                "correlated scalar subquery must be an aggregate")
        inner, corr, residual = self.plan_inner_with_correlation(outer, subq)
        if residual:
            raise AnalysisError(
                f"non-equi correlated scalar subquery: {residual}")
        if not corr:
            raise AnalysisError(
                "uncorrelated scalar subquery reached the correlated path")

        # synthesize: SELECT k1.., <agg expr> GROUP BY k1..
        group_asts = []
        for _, icol in corr:
            parts = (icol.qualifier, icol.name) if icol.qualifier \
                else (icol.name,)
            group_asts.append(A.Identifier(parts))
        select = tuple(A.SelectItem(g, f"$ck{i}")
                       for i, g in enumerate(group_asts)) + \
            (A.SelectItem(subq.select[0].expr, "$val"),)
        synth = A.Query(select=select, distinct=False, relation=None,
                        where=None, group_by=tuple(group_asts),
                        having=None, order_by=(), limit=None)
        agg_rel, _, _ = self.plan_aggregation(synth, inner)

        k = len(corr)
        # LEFT join: outer rows with an empty correlated group survive
        # with a NULL value column (SQL scalar-subquery-over-empty
        # semantics); see the marker handling below
        join = self.make_join(
            "left", outer.node, agg_rel.node,
            tuple(o for o, _ in corr), tuple(range(k)), None, True,
            probe_fields=[self._scope_field(outer.scope, o)
                          for o, _ in corr],
            build_fields=[agg_rel.scope.columns[i].field
                          for i in range(k)])
        out = join.output
        n_outer = len(outer.node.output)
        val_name, val_t = agg_rel.node.output[k]
        # splice the subquery's value column into the predicate: replace
        # the ScalarSubquery AST with a hidden identifier bound to it,
        # then lower the whole conjunct (arithmetic around the subquery
        # included) over outer ++ value.
        # Empty-group semantics: the LEFT join leaves the value NULL for
        # outer rows with no correlated group — correct for sum/avg/min/
        # max (NULL over empty) and for comparisons (unknown filters the
        # row); a BARE count is 0 over an empty group, so it coalesces.
        marker: A.Node = A.Identifier(("$corrval",))
        sel = subq.select[0].expr
        bare_count = isinstance(sel, A.FunctionCall) and \
            sel.name == "count"
        if not bare_count:
            for node_ in walk_ast(sel):
                if isinstance(node_, A.FunctionCall) and \
                        node_.name == "count":
                    raise AnalysisError(
                        "correlated scalar subquery mixing count() into "
                        "a larger expression is not supported (empty "
                        "groups would need per-expression evaluation)")
        if bare_count:
            marker = A.FunctionCall("coalesce",
                                    (marker, A.NumberLit("0")))
        pred_ast = ast_replace(conjunct, sub, marker)
        scope2 = Scope(list(outer.scope.columns) +
                       [ScopeColumn(None, "$corrval", val_t,
                                    n_outer + k, None)])
        low = ExpressionLowerer(scope2, planner=self)
        pred = low.to_bool(low.lower(pred_ast))
        node = L.FilterNode(join, pred, out)
        # visible scope stays the outer's; joined agg columns are hidden
        return PlannedRelation(node, outer.scope)

    def resolve_order_expr(self, ast: A.Node, q: A.Query,
                           rel: PlannedRelation, names: List[str]) -> int:
        # ordinal
        if isinstance(ast, A.NumberLit) and "." not in ast.text:
            i = int(ast.text) - 1
            if not (0 <= i < len(names)):
                raise AnalysisError(f"ORDER BY position {i+1} out of range")
            return i
        # alias or column name in output
        if isinstance(ast, A.Identifier) and len(ast.parts) == 1:
            nm = ast.parts[0].lower()
            if nm in names:
                return names.index(nm)
        # expression identical to some select item
        for i, item in enumerate(q.select):
            if item.expr is not None and ast_equal(ast, item.expr, q):
                return i
        raise AnalysisError(
            "ORDER BY expressions must reference select outputs for now")


# --------------------------------------------------------------------------
# small helpers
# --------------------------------------------------------------------------

def split_conjuncts(node: A.Node, out: List[A.Node]) -> None:
    if isinstance(node, A.BinaryOp) and node.op == "and":
        split_conjuncts(node.left, out)
        split_conjuncts(node.right, out)
    else:
        out.append(node)


def add_or_common_conjuncts(conjuncts: List[A.Node]) -> None:
    """For each OR conjunct, pull out predicates present in every branch
    (sound: the OR implies them). TPC-H q19's join key p_partkey=l_partkey
    lives inside each OR block; Trino's ExtractCommonPredicatesExpression-
    Rewrite (sql/ir/optimizer/) performs the same extraction. The original
    OR stays as a residual filter."""
    extracted: List[A.Node] = []
    for c in conjuncts:
        branches: List[A.Node] = []
        split_disjuncts(c, branches)
        if len(branches) < 2:
            continue
        branch_conjs = []
        for b in branches:
            bc: List[A.Node] = []
            split_conjuncts(b, bc)
            branch_conjs.append(bc)
        for cand in branch_conjs[0]:
            if all(cand in bc for bc in branch_conjs[1:]):
                if cand not in conjuncts and cand not in extracted:
                    extracted.append(cand)
    conjuncts.extend(extracted)


def split_disjuncts(node: A.Node, out: List[A.Node]) -> None:
    if isinstance(node, A.BinaryOp) and node.op == "or":
        split_disjuncts(node.left, out)
        split_disjuncts(node.right, out)
    else:
        out.append(node)


def as_equi(node: A.Node):
    if isinstance(node, A.BinaryOp) and node.op == "=" and \
            isinstance(node.left, A.Identifier) and \
            isinstance(node.right, A.Identifier):
        return node.left.parts, node.right.parts
    return None


def walk_ast(node: A.Node):
    from .analyzer import ast_children
    yield node
    for ch in ast_children(node):
        yield from walk_ast(ch)


def collect_scalar_subqueries(node: A.Node, out: list) -> None:
    """Find ScalarSubquery nodes in a predicate (not descending into
    nested queries — each subquery is handled at its own level)."""
    from .analyzer import ast_children
    if isinstance(node, A.ScalarSubquery):
        out.append(node)
        return
    if isinstance(node, (A.Query, A.SetOp)):
        return
    for ch in ast_children(node):
        collect_scalar_subqueries(ch, out)


def ast_replace(root: A.Node, target: A.Node, replacement: A.Node) -> A.Node:
    """Rebuild an AST with `target` (by identity) swapped for
    `replacement`; untouched subtrees keep their identity."""
    import dataclasses as _dc
    if root is target:
        return replacement
    if not _dc.is_dataclass(root):
        return root
    changes = {}
    for f in _dc.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, A.Node):
            nv = ast_replace(v, target, replacement)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and any(isinstance(x, A.Node)
                                          for x in v):
            nv = tuple(ast_replace(x, target, replacement)
                       if isinstance(x, A.Node) else x for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return _dc.replace(root, **changes) if changes else root


def collect_grouping_calls(node: A.Node, out: list) -> None:
    """Find grouping(...) calls (GroupingOperationRewriter's discovery
    step); window arguments are excluded like collect_windows' are."""
    from .analyzer import ast_children
    if isinstance(node, A.FunctionCall) and node.name == "grouping":
        if node not in out:
            out.append(node)
        return
    for ch in ast_children(node):
        collect_grouping_calls(ch, out)


def ast_equal(a: A.Node, b: A.Node, q: A.Query) -> bool:
    """Syntactic equality; also matches a bare identifier against a select
    alias (SQL: GROUP BY can reference aliases in some dialects — Trino
    allows ordinals and output names; we match structurally)."""
    return a == b


def resolve_ordinal(g: A.Node, q: A.Query) -> A.Node:
    if isinstance(g, A.NumberLit) and "." not in g.text:
        i = int(g.text) - 1
        if 0 <= i < len(q.select) and q.select[i].expr is not None:
            return q.select[i].expr
    return g


def default_name(expr: A.Node) -> str:
    if isinstance(expr, A.Identifier):
        return expr.parts[-1]
    if isinstance(expr, A.FunctionCall):
        return expr.name
    return "_col"


def sum_type(t: DataType) -> DataType:
    if t.kind is TypeKind.DECIMAL:
        from ..types import decimal as mk
        # the reference's sum(decimal(p,s)) -> decimal(38,s)
        # (DecimalAggregation); device accumulation is two int64 limbs
        return mk(38, t.scale)
    if t.kind is TypeKind.DOUBLE:
        return DOUBLE
    return BIGINT


def sub_fields(sub: "PlannedRelation"):
    """Fields (with dictionaries) for a subquery's output columns."""
    return [c.field for c in sub.scope.columns]


def _div_half_up(v: int, div: int) -> int:
    """Integer divide rounding HALF_UP away from zero — identical to the
    runtime ir.Cast rescale so plan-time folding can't diverge."""
    q, r = divmod(abs(v), div)
    if 2 * r >= div:
        q += 1
    return q if v >= 0 else -q


def _convert_const(value, src: Optional[DataType], dst: DataType):
    """Convert a plan-time constant between logical types (VALUES cell
    coercion; Trino's TypeCoercion applied to bound constants). Rounding
    is HALF_UP away from zero, matching the runtime Cast kernels."""
    import math
    if value is None or src is None:
        return None
    if src == dst:
        return value
    sk, dk = src.kind, dst.kind
    if dk is TypeKind.DECIMAL:
        if sk is TypeKind.DECIMAL:
            diff = dst.scale - src.scale
            return value * 10 ** diff if diff >= 0 \
                else _div_half_up(value, 10 ** -diff)
        if sk in (TypeKind.BIGINT, TypeKind.INTEGER):
            return value * 10 ** dst.scale
        if sk is TypeKind.DOUBLE:
            scaled = abs(value) * 10 ** dst.scale
            return int(math.floor(scaled + 0.5)) * (1 if value >= 0 else -1)
    if dk is TypeKind.DOUBLE:
        if sk is TypeKind.DECIMAL:
            return value / 10 ** src.scale
        return float(value)
    if dk in (TypeKind.BIGINT, TypeKind.INTEGER):
        if sk is TypeKind.DECIMAL:
            return _div_half_up(value, 10 ** src.scale)
        if sk is TypeKind.DOUBLE:
            return int(math.floor(abs(value) + 0.5)) * \
                (1 if value >= 0 else -1)
        return int(value)
    if dk is TypeKind.VARCHAR and sk is TypeKind.VARCHAR:
        return value
    if dk is TypeKind.DATE and sk is TypeKind.DATE:
        return value
    raise AnalysisError(f"cannot cast constant from {src} to {dst}")


def _cast_relation(rel: PlannedRelation, casts) -> PlannedRelation:
    """Wrap a set-op side in a cast projection where column types differ
    from the unified output type (AddExchanges inserts the same coercion
    projections under UnionNode in the reference)."""
    if all(c is None for c in casts):
        return rel
    exprs, output, cols = [], [], []
    for i, (c, sc) in enumerate(zip(casts, rel.scope.columns)):
        ref = ir.ColumnRef(i, sc.dtype, sc.name)
        if c is None:
            exprs.append(ref)
            output.append((sc.name, sc.dtype))
            cols.append(ScopeColumn(sc.qualifier, sc.name, sc.dtype, i,
                                    sc.field))
        else:
            exprs.append(ir.Cast(ref, c))
            output.append((sc.name, c))
            cols.append(ScopeColumn(sc.qualifier, sc.name, c, i, None))
    node = L.ProjectNode(rel.node, tuple(exprs), tuple(output))
    return PlannedRelation(node, Scope(cols))
