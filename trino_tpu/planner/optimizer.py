"""Plan optimizer passes.

Reference: Trino runs 113 ordered optimizer passes (PlanOptimizers.java:274).
The load-bearing ones for this engine so far:

- predicate pushdown and join-key extraction happen during planning
  (planner.py, mirroring PredicatePushDown + equi-clause extraction)
- column pruning (this file) — PruneUnreferencedOutputs: restrict every
  scan to the columns the query actually touches and renumber references.
  On columnar TPU execution this directly cuts HBM traffic and
  host->device transfer, the analog of its I/O saving in the reference.
"""

from __future__ import annotations

from typing import Dict, Tuple

from .. import ir
from . import logical as L


def prune_plan(root: L.OutputNode) -> L.OutputNode:
    n = len(root.child.output)
    child, mapping = _prune(root.child, frozenset(range(n)))
    # root requires every column; restore identity order if pruning
    # renumbered anything
    if len(child.output) != n or \
            not all(mapping.get(i) == i for i in range(n)):
        child = L.ProjectNode(
            child,
            tuple(ir.ColumnRef(mapping[i], root.child.output[i][1])
                  for i in range(n)),
            tuple(root.child.output))
    child = push_scan_predicates(child)
    return L.OutputNode(child, root.names, tuple(root.child.output))


def pushable_conjuncts(predicate: ir.Expr):
    """Split a predicate into top-level AND conjuncts and keep the ones a
    zone map can evaluate: single-column range/equality/IN/IS [NOT] NULL
    with literal bounds (TupleDomain extraction,
    DomainTranslator.getExtractionResult in the reference). NOT / OR /
    casts / multi-column shapes are skipped — they stay residual-only."""
    out = []
    stack = [predicate]
    while stack:
        e = stack.pop()
        if isinstance(e, ir.Logical) and e.op == "and":
            stack.extend(e.args)
            continue
        if isinstance(e, ir.Compare):
            lc = isinstance(e.left, ir.ColumnRef) and \
                isinstance(e.right, ir.Literal)
            rc = isinstance(e.right, ir.ColumnRef) and \
                isinstance(e.left, ir.Literal)
            if lc:
                out.append(e)
            elif rc:
                flip = {"=": "=", "<>": "<>", "<": ">", "<=": ">=",
                        ">": "<", ">=": "<="}
                out.append(ir.Compare(flip[e.op], e.right, e.left))
        elif isinstance(e, ir.Between):
            if isinstance(e.arg, ir.ColumnRef) and \
                    isinstance(e.low, ir.Literal) and \
                    isinstance(e.high, ir.Literal):
                out.append(e)
        elif isinstance(e, ir.InList):
            if isinstance(e.arg, ir.ColumnRef) and \
                    all(isinstance(v, ir.Literal) for v in e.values):
                out.append(e)
        elif isinstance(e, ir.IsNull):
            if isinstance(e.arg, ir.ColumnRef):
                out.append(e)
        elif isinstance(e, ir.DictPredicate):
            # varchar =/range/LIKE/IN lower to a code->bool LUT; pools are
            # sorted, so zone [min_code, max_code] bounds evaluate it
            if isinstance(e.arg, ir.ColumnRef):
                out.append(e)
    return out


def push_scan_predicates(node: L.PlanNode) -> L.PlanNode:
    """Copy the zone-map-evaluable conjuncts of every Filter sitting
    directly above a ScanNode into the scan's advisory `predicate` slot.
    The Filter itself is untouched: it is the residual that guarantees
    bit-exact results whether or not execution skips anything."""
    import dataclasses as _dc
    if isinstance(node, L.FilterNode) and \
            isinstance(node.child, L.ScanNode) and \
            node.child.catalog not in ("system", "information_schema"):
        conj = pushable_conjuncts(node.predicate)
        if conj:
            pushed = conj[0] if len(conj) == 1 else \
                ir.Logical("and", tuple(conj))
            return _dc.replace(
                node, child=_dc.replace(node.child, predicate=pushed))
        return node
    changes = {}
    for f in _dc.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, L.PlanNode):
            nv = push_scan_predicates(v)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and \
                all(isinstance(x, L.PlanNode) for x in v):
            nt = tuple(push_scan_predicates(x) for x in v)
            if any(a is not b for a, b in zip(nt, v)):
                changes[f.name] = nt
    return _dc.replace(node, **changes) if changes else node


def _identity(n: int) -> Dict[int, int]:
    return {i: i for i in range(n)}


def _narrow_to(node: L.PlanNode, mapping: Dict[int, int],
               needed) -> Tuple[L.PlanNode, Dict[int, int]]:
    """Project `node` down to exactly the columns `needed` (old indices)
    when it kept extras; mapping entries outside `needed` drop."""
    keep = sorted({mapping[i] for i in needed})
    if len(keep) >= len(node.output):
        return node, mapping
    remap = {old: new for new, old in enumerate(keep)}
    proj = L.ProjectNode(
        node,
        tuple(ir.ColumnRef(i, node.output[i][1]) for i in keep),
        tuple(node.output[i] for i in keep))
    return proj, {orig: remap[m] for orig, m in mapping.items()
                  if m in remap}


def _prune(node: L.PlanNode, needed: frozenset):
    """Returns (new_node, mapping old_index -> new_index). The new node's
    output covers at least `needed` (supersets allowed)."""

    if isinstance(node, L.ScanNode):
        keep = sorted(needed) if needed else [0]
        mapping = {old: new for new, old in enumerate(keep)}
        predicate = node.predicate
        if predicate is not None:
            refs = ir.referenced_columns(predicate)
            if refs <= set(keep):
                predicate = ir.remap_columns(predicate, mapping)
            else:
                # a referenced column was pruned away: dropping the
                # pushdown is always safe (it only enables skipping)
                predicate = None
        return L.ScanNode(
            node.catalog, node.schema_name, node.table, node.table_schema,
            tuple(node.column_indices[i] for i in keep),
            tuple(node.output[i] for i in keep),
            predicate=predicate), mapping

    if isinstance(node, L.FilterNode):
        child_needed = needed | ir.referenced_columns(node.predicate)
        child, m = _prune(node.child, frozenset(child_needed))
        return L.FilterNode(child, ir.remap_columns(node.predicate, m),
                            child.output), m

    if isinstance(node, L.ProjectNode):
        # empty keep is fine: a zero-column projection still carries the
        # live mask (count(*)-only aggregations need nothing else)
        keep = sorted(needed)
        child_needed = set()
        for i in keep:
            child_needed |= ir.referenced_columns(node.exprs[i])
        child, m = _prune(node.child, frozenset(child_needed))
        exprs = tuple(ir.remap_columns(node.exprs[i], m) for i in keep)
        output = tuple(node.output[i] for i in keep)
        mapping = {old: new for new, old in enumerate(keep)}
        return L.ProjectNode(child, exprs, output), mapping

    if isinstance(node, L.AggregateNode):
        child_needed = set(node.group_keys)
        for a in node.aggs:
            if a.arg is not None:
                child_needed |= ir.referenced_columns(a.arg)
        child, m = _prune(node.child, frozenset(child_needed))
        aggs = tuple(
            L.AggSpecNode(a.func,
                          None if a.arg is None
                          else ir.remap_columns(a.arg, m),
                          a.out_name, a.out_dtype, a.distinct)
            for a in node.aggs)
        return L.AggregateNode(
            child, tuple(m[k] for k in node.group_keys), aggs,
            node.strategy, node.key_domains, node.out_capacity,
            node.output), _identity(len(node.output))

    if isinstance(node, L.JoinNode):
        n_probe = len(node.left.output)
        # the residual addresses the probe++build pair layout, even for
        # semi/anti joins whose own output is probe-only
        res_refs = set() if node.residual is None else \
            ir.referenced_columns(node.residual)
        probe_needed = {i for i in needed if i < n_probe} | \
            set(node.left_keys) | {i for i in res_refs if i < n_probe}
        build_needed = {i - n_probe for i in needed if i >= n_probe} | \
            set(node.right_keys) | \
            {i - n_probe for i in res_refs if i >= n_probe}
        left, ml = _prune(node.left, frozenset(probe_needed))
        right, mr = _prune(node.right, frozenset(build_needed))
        # children may keep MORE than needed (supersets: their own
        # filter/key columns). Dead columns in a join's input are not
        # just metadata — the build batch carries them at runtime,
        # growing every payload gather and defeating value-packed LUTs
        # — so narrow each side with a projection when it over-kept.
        left, ml = _narrow_to(left, ml, probe_needed)
        right, mr = _narrow_to(right, mr, build_needed)
        n_new_probe = len(left.output)
        # pair mapping covers probe++build regardless of join kind (the
        # residual uses it); the returned mapping is restricted to the
        # node's own output layout (probe-only for semi/anti)
        pair_mapping = {}
        for old, new in ml.items():
            pair_mapping[old] = new
        for old, new in mr.items():
            pair_mapping[n_probe + old] = n_new_probe + new
        mapping = {old: new for old, new in pair_mapping.items()
                   if old < len(node.output)}
        residual = None if node.residual is None else \
            ir.remap_columns(node.residual, pair_mapping)
        if node.kind == "mark":
            # output = probe ++ $mark: the mark column rides along at the
            # end regardless of probe pruning
            output = tuple(left.output) + (node.output[n_probe],)
            mapping[n_probe] = n_new_probe
        elif node.kind in ("inner", "left"):
            output = tuple(left.output) + tuple(right.output)
        else:
            output = tuple(left.output)
        return L.JoinNode(
            node.kind, left, right,
            tuple(ml[k] for k in node.left_keys),
            tuple(mr[k] for k in node.right_keys),
            residual, node.build_unique, output,
            null_aware=node.null_aware,
            distribution=node.distribution,
            build_key_domain=node.build_key_domain), mapping

    if isinstance(node, L.WindowNode):
        c = len(node.child.output)
        child_needed = {i for i in needed if i < c} | \
            set(node.partition_by) | {k.index for k in node.order_by} | \
            {s.arg for s in node.specs if s.arg is not None}
        child, m = _prune(node.child, frozenset(child_needed))
        nc = len(child.output)
        specs = tuple(
            L.WinSpecNode(s.func, None if s.arg is None else m[s.arg],
                          s.frame, s.offset, s.default, s.out_name,
                          s.out_dtype)
            for s in node.specs)
        mapping = dict(m)
        for j in range(len(node.specs)):
            mapping[c + j] = nc + j
        return L.WindowNode(
            child, tuple(m[i] for i in node.partition_by),
            tuple(L.SortKey(m[k.index], k.ascending, k.nulls_first)
                  for k in node.order_by),
            specs,
            tuple(child.output) + tuple(node.output[c:])), mapping

    if isinstance(node, L.UnnestNode):
        c = len(node.child.output)
        child_needed = {i for i in needed if i < c} | {node.array_col}
        child, m = _prune(node.child, frozenset(child_needed))
        nc = len(child.output)
        mapping = dict(m)
        mapping[c] = nc                       # element column
        if node.ordinality:
            mapping[c + 1] = nc + 1
        return L.UnnestNode(
            child, m[node.array_col], node.array_pool,
            node.element_name, node.element_dtype, node.element_pool,
            node.ordinality,
            tuple(child.output) + tuple(node.output[c:])), mapping

    if isinstance(node, L.SortNode):
        child_needed = needed | {k.index for k in node.keys}
        child, m = _prune(node.child, frozenset(child_needed))
        keys = tuple(L.SortKey(m[k.index], k.ascending, k.nulls_first)
                     for k in node.keys)
        return L.SortNode(child, keys, node.limit, child.output), m

    if isinstance(node, L.LimitNode):
        child, m = _prune(node.child, needed)
        return L.LimitNode(child, node.count, child.output), m

    if isinstance(node, L.ValuesNode):
        keep = sorted(needed)
        mapping = {old: new for new, old in enumerate(keep)}
        return L.ValuesNode(
            tuple(node.arrays[i] for i in keep),
            tuple(node.valids[i] for i in keep),
            node.num_rows,
            tuple(node.fields[i] for i in keep),
            tuple(node.output[i] for i in keep)), mapping

    if isinstance(node, L.MultiJoinNode):
        # The fused star probe consumes every fact/dim column that the
        # ladder it replaces would have; keep children exact (scans
        # beneath them still prune via their own Project/Filter layers)
        fact = _prune_exact(node.fact,
                            frozenset(range(len(node.fact.output))))
        dims = tuple(_prune_exact(d, frozenset(range(len(d.output))))
                     for d in node.dims)
        return L.MultiJoinNode(
            fact, dims, node.fact_keys, node.dim_keys, node.dim_domains,
            node.output, node.distribution), _identity(len(node.output))

    if isinstance(node, L.SetOpNode):
        # distinct/intersect/except semantics are over the whole row:
        # children must keep every column, in order
        nall = frozenset(range(len(node.output)))
        left = _prune_exact(node.left, nall)
        right = _prune_exact(node.right, nall)
        return L.SetOpNode(node.op, left, right, node.left_remaps,
                           node.right_remaps,
                           node.output), _identity(len(node.output))

    raise NotImplementedError(type(node).__name__)


def _prune_exact(node: L.PlanNode, needed: frozenset) -> L.PlanNode:
    """Prune a subtree but guarantee the original column order/layout
    (re-projecting if the child renumbered anything)."""
    n = len(node.output)
    child, mapping = _prune(node, needed)
    if len(child.output) == n and all(mapping.get(i) == i
                                      for i in range(n)):
        return child
    return L.ProjectNode(
        child,
        tuple(ir.ColumnRef(mapping[i], node.output[i][1])
              for i in range(n)),
        tuple(node.output))
