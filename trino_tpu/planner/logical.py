"""Logical plan nodes.

Reference: Trino's 66 PlanNode kinds (core/trino-main/.../sql/planner/plan/).
We model the executed subset; each node's `output` is an ordered list of
(name, DataType) pairs, and expressions reference child output columns by
position (like Trino's Symbol-resolved plans, but positional — a deliberate
simplification that suits array programs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .. import ir
from ..batch import Schema
from ..types import DataType, TypeKind


@dataclass(frozen=True)
class PlanNode:
    pass


@dataclass(frozen=True)
class ScanNode(PlanNode):
    """TableScanNode (sql/planner/plan/TableScanNode.java) — reads a
    connector table; column pruning happens via `column_indices`."""
    catalog: str
    schema_name: str
    table: str
    table_schema: Schema              # full connector schema
    column_indices: Tuple[int, ...]   # which connector columns we read
    output: Tuple                     # ((name, DataType), ...)
    # conjunctive single-column predicate pushed down by the optimizer
    # (TupleDomain pushdown in the reference). Advisory only: execution
    # may use it to skip zones/splits that provably cannot match, but the
    # residual FilterNode above always re-applies the full predicate, so
    # dropping it is always safe. References are scan OUTPUT positions.
    predicate: Optional[ir.Expr] = None


@dataclass(frozen=True)
class FilterNode(PlanNode):
    child: PlanNode
    predicate: ir.Expr
    output: Tuple


@dataclass(frozen=True)
class ProjectNode(PlanNode):
    child: PlanNode
    exprs: Tuple                      # tuple[ir.Expr, ...]
    output: Tuple


@dataclass(frozen=True)
class AggSpecNode:
    func: str                         # sum|count|count_star|min|max|avg
    arg: Optional[ir.Expr]            # over child output
    out_name: str
    out_dtype: DataType
    distinct: bool = False


@dataclass(frozen=True)
class AggregateNode(PlanNode):
    """AggregationNode; group_keys are child output column indices.
    `strategy` chosen by the optimizer: 'direct' (dense dict-code domain),
    'sort' (general), or 'global' (no keys)."""
    child: PlanNode
    group_keys: Tuple[int, ...]
    aggs: Tuple                       # tuple[AggSpecNode, ...]
    strategy: str
    key_domains: Tuple[int, ...]      # for 'direct'
    out_capacity: int                 # for 'sort'
    output: Tuple


@dataclass(frozen=True)
class JoinNode(PlanNode):
    """JoinNode (sql/planner/plan/JoinNode.java). Equi-join; left side is
    the probe, right side the build (LookupJoinOperator convention:
    HashBuilderOperator consumes the build side)."""
    kind: str                         # inner|left|semi|anti|mark
    left: PlanNode                    # probe
    right: PlanNode                   # build
    left_keys: Tuple[int, ...]
    right_keys: Tuple[int, ...]
    residual: Optional[ir.Expr]       # over concatenated output
    build_unique: bool                # planner's guarantee/assumption
    output: Tuple
    null_aware: bool = False          # NOT IN semantics (anti only)
    # cost-chosen exchange strategy for the build side on a mesh
    # (DetermineJoinDistributionType.java:51): REPLICATED vs PARTITIONED
    distribution: str = "auto"        # auto|broadcast|partitioned
    # dense-LUT probe domain (exclusive key upper bound) when connector
    # stats prove the single build key lives in [0, domain) — the
    # BigintGroupByHash-style fast path; None = sorted+searchsorted
    build_key_domain: Optional[int] = None


@dataclass(frozen=True)
class MultiJoinNode(PlanNode):
    """Fused star join: one fact relation inner-joined to k snowflaked
    dimension builds on conjunctive single-column equi-keys, probed in
    ONE Pallas pass (ops/pallas_hash.multiway_probe). Emitted by the
    planner's star detector (fuse_star_joins) as the fusion of a
    pairwise JoinNode ladder; `multijoin_to_ladder` reconstructs that
    ladder exactly, so every degrade path is bit-exact by construction.

    The fact side is AUTHORITATIVE: unlike the pairwise path, the
    executor never re-derives probe/build orientation per hop, so a
    mis-sized dimension can't silently flip the fact table into a VMEM
    build — it degrades that one dimension to the pairwise ladder
    instead.  `output` is the ladder-top layout: fact columns, then
    each dimension's columns in join order (dims[0] = bottom hop)."""
    fact: PlanNode
    dims: Tuple[PlanNode, ...]
    fact_keys: Tuple[Tuple[int, ...], ...]   # per dim, fact-side keys
    dim_keys: Tuple[Tuple[int, ...], ...]    # per dim, build-side keys
    # per-dim dense-LUT domains, preserved so the reconstructed ladder
    # keeps the original JoinNodes' fast paths
    dim_domains: Tuple[Optional[int], ...]
    output: Tuple
    distribution: str = "broadcast"


@dataclass(frozen=True)
class WinSpecNode:
    """One window function (plan-level mirror of ops.window.WinSpec)."""
    func: str                         # row_number|rank|dense_rank|ntile|
                                      # lead|lag|first_value|last_value|
                                      # sum|count|count_star|min|max
    arg: Optional[int]                # child output column index
    frame: str                        # partition|range_running|rows_running
    offset: int                       # lead/lag offset, ntile buckets
    default: Optional[object]         # lead/lag default literal
    out_name: str
    out_dtype: DataType


@dataclass(frozen=True)
class WindowNode(PlanNode):
    """WindowNode (sql/planner/plan/WindowNode.java): appends one column
    per function; all functions share (partition_by, order_by)."""
    child: PlanNode
    partition_by: Tuple[int, ...]     # child output column indices
    order_by: Tuple                   # tuple[SortKey, ...]
    specs: Tuple                      # tuple[WinSpecNode, ...]
    output: Tuple


@dataclass(frozen=True)
class UnnestNode(PlanNode):
    """UNNEST lateral expansion (operator/unnest/UnnestOperator.java:42):
    each input row repeats once per element of its array; output = child
    columns ++ element column (++ ordinality). Arrays follow the pool-id
    discipline (types.py), so expansion runs at the host edge like the
    other pool transforms."""
    child: PlanNode
    array_col: int                    # child output column (pool ids)
    array_pool: Tuple                 # id -> tuple of elements
    element_name: str
    element_dtype: "DataType"
    element_pool: Optional[Tuple]     # varchar elements: their dict pool
    ordinality: bool
    output: Tuple


@dataclass(frozen=True)
class SortKey:
    index: int
    ascending: bool
    nulls_first: bool


@dataclass(frozen=True)
class SortNode(PlanNode):
    child: PlanNode
    keys: Tuple                       # tuple[SortKey, ...]
    limit: Optional[int]              # TopN fusion (TopNOperator)
    output: Tuple


@dataclass(frozen=True)
class LimitNode(PlanNode):
    child: PlanNode
    count: int
    output: Tuple


@dataclass(frozen=True, eq=False)
class ValuesNode(PlanNode):
    """Inline table of constants (sql/planner/plan/ValuesNode.java).
    Cell values are evaluated at plan time; arrays are host numpy columns
    (VARCHAR already dictionary-encoded, dictionaries in `fields`)."""
    arrays: Tuple                     # tuple[np.ndarray, ...]
    valids: Tuple                     # tuple[np.ndarray, ...]
    num_rows: int
    fields: Tuple                     # tuple[batch.Field, ...]
    output: Tuple


@dataclass(frozen=True)
class SetOpNode(PlanNode):
    """UNION/INTERSECT/EXCEPT (plan/UnionNode.java, IntersectNode.java,
    ExceptNode.java). Children are type-aligned by the planner; VARCHAR
    columns share a merged dictionary, with `right_remaps` holding the
    old-code -> merged-code LUT per column (None = identity).

    'union_all' concatenates on device; the DISTINCT/INTERSECT/EXCEPT
    variants run host-side (Trino lowers them to aggregation + join —
    these are cold paths by row volume)."""
    op: str                           # union|union_all|intersect|
                                      # intersect_all|except|except_all
    left: PlanNode
    right: PlanNode
    left_remaps: Tuple                # tuple[Optional[tuple[int,...]], ...]
    right_remaps: Tuple               # tuple[Optional[tuple[int,...]], ...]
    output: Tuple


@dataclass(frozen=True)
class RemoteSourceNode(PlanNode):
    """Consumes another fragment's output (sql/planner/plan/
    RemoteSourceNode.java): the cut point the fragmenter leaves behind.
    At schedule time the producing fragment's materialized output is
    substituted here (broadcast distribution ships it inside the consumer
    fragment; the executor never sees this node)."""
    fragment_id: int
    output: Tuple


@dataclass(frozen=True)
class OutputNode(PlanNode):
    """Root: names the result columns (sql/planner/plan/OutputNode.java)."""
    child: PlanNode
    names: Tuple[str, ...]
    output: Tuple


@dataclass(frozen=True)
class TableWriterNode(PlanNode):
    """Partitioned write stage root (sql/planner/plan/TableWriterNode.java):
    the subtree's rows are staged to a uniquely-named attempt file under
    the target table's `.staging/` directory — never published by the
    worker. `fields` carries the concrete output Fields (dictionaries
    included) so a write task can rebuild TableData from exchange pages;
    `attempt` makes every task attempt's staging file unique."""
    child: PlanNode
    catalog: str
    schema_name: str
    table: str
    table_dir: str
    fmt: str                          # "orc" | "parquet"
    query_id: str
    stage: int
    partition: int
    attempt: str
    fields: Tuple                     # Tuple[Field, ...]
    output: Tuple                     # (("rows", BIGINT),)


@dataclass(frozen=True)
class TableCommitNode(PlanNode):
    """Coordinator-side commit root (TableFinishNode.java's role): dedups
    staged-file manifests by (stage, partition) first-success-wins, writes
    the CRC-framed commit journal, publishes by atomic rename, bumps the
    catalog version. Executes on the coordinator only — the scheduler
    interprets it; the executor never sees it."""
    child: PlanNode
    catalog: str
    schema_name: str
    table: str
    query_id: str
    output: Tuple


def children(node: PlanNode):
    if isinstance(node, (FilterNode, ProjectNode, AggregateNode, SortNode,
                         LimitNode, OutputNode, WindowNode, UnnestNode,
                         TableWriterNode, TableCommitNode)):
        return (node.child,)
    if isinstance(node, (JoinNode, SetOpNode)):
        return (node.left, node.right)
    if isinstance(node, MultiJoinNode):
        return (node.fact,) + node.dims
    return ()


def replace_nodes(root: PlanNode, mapping) -> PlanNode:
    """Rebuild the (frozen) tree with `mapping[id(node)] -> new node`
    substitutions applied; untouched subtrees keep their identity."""
    import dataclasses as _dc
    hit = mapping.get(id(root))
    if hit is not None:
        return hit
    changes = {}
    for f in _dc.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, PlanNode):
            nv = replace_nodes(v, mapping)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and \
                all(isinstance(x, PlanNode) for x in v):
            nv = tuple(replace_nodes(x, mapping) for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return _dc.replace(root, **changes) if changes else root


# --------------------------------------------------------------------------
# star detection: fuse a pairwise JoinNode ladder into one MultiJoinNode
# --------------------------------------------------------------------------

# key kinds the fused kernel can probe: `_combined_key` packs these into
# one int64 losslessly (VARCHAR rides its dictionary codes — make_join's
# `$jk` pool alignment guarantees both sides share a pool).  DOUBLE and
# DECIMAL would truncate through the int64 pack.
_STAR_KEY_KINDS = (TypeKind.BIGINT, TypeKind.INTEGER, TypeKind.BOOLEAN,
                   TypeKind.DATE, TypeKind.TIMESTAMP, TypeKind.VARCHAR)


def _spine_has_join(node: PlanNode) -> bool:
    while isinstance(node, FilterNode):
        node = node.child
    return isinstance(node, JoinNode)


def _star_hop_ok(j: JoinNode, n_fact: int) -> Optional[str]:
    """None if the hop can join the fused star, else the decline reason
    (surfaced verbatim in EXPLAIN's star verdict)."""
    if j.kind != "inner":
        return "non-inner hop"
    if j.residual is not None:
        return "residual predicate on hop"
    if j.null_aware:
        return "null-aware hop"
    if not j.build_unique:
        return "build not provably unique"
    if len(j.left_keys) != 1:
        return "multi-column key"
    if j.left_keys[0] >= n_fact:
        # the probe key is a column PRODUCED by an earlier dimension:
        # it does not exist in the fact batch the single pass probes
        return "snowflake key (dim-derived)"
    if j.left.output[j.left_keys[0]][1].kind not in _STAR_KEY_KINDS or \
            j.right.output[j.right_keys[0]][1].kind not in _STAR_KEY_KINDS:
        return "non-integer key"
    return None


def collect_star(root: PlanNode, max_dims: int):
    """Walk the probe spine of a join ladder (JoinNodes, with conjunct
    FilterNodes interleaved) bottom-up, committing the longest fusable
    prefix of hops.  Returns None when the spine holds fewer than two
    joins, else (fact, hops, hoisted, upper, note):

    - `fact`    first non-spine node (the probe side of the bottom hop)
    - `hops`    committed JoinNodes, bottom-up (possibly < 2: declined)
    - `hoisted` FilterNodes that sat BETWEEN committed hops, bottom-up;
      their predicates reference prefix columns of the fused layout, so
      they re-apply above the MultiJoinNode without remapping
    - `upper`   spine nodes (top-down) left above the fusion point
    - `note`    why fusion stopped (None = every hop committed)
    """
    spine = []
    node = root
    while True:
        if isinstance(node, FilterNode) and _spine_has_join(node.child):
            spine.append(node)
            node = node.child
        elif isinstance(node, JoinNode):
            spine.append(node)
            node = node.left
        else:
            break
    fact = node
    if sum(1 for n in spine if isinstance(n, JoinNode)) < 2:
        return None
    n_fact = len(fact.output)
    hops, hoisted, pend_filters = [], [], []
    note = None
    cut = len(spine)
    for idx in range(len(spine) - 1, -1, -1):
        nd = spine[idx]
        if isinstance(nd, FilterNode):
            pend_filters.append(nd)
            continue
        why = _star_hop_ok(nd, n_fact)
        if why is None and len(hops) >= max_dims:
            why = f"dim cap ({max_dims})"
        if why is not None:
            note = why
            break
        hops.append(nd)
        hoisted.extend(pend_filters)
        pend_filters = []
        cut = idx
    return fact, hops, hoisted, spine[:cut], note


def fuse_star_joins(root: PlanNode, max_dims: int) -> PlanNode:
    """Rewrite the longest fusable star prefix of `root`'s join ladder
    into a MultiJoinNode (identity when nothing qualifies).  The fused
    node's output equals the topmost committed hop's, so everything
    above re-attaches unchanged."""
    import dataclasses as _dc
    got = collect_star(root, max_dims)
    if got is None:
        return root
    fact, hops, hoisted, upper, _note = got
    if len(hops) < 2:
        return root
    cur: PlanNode = MultiJoinNode(
        fact=fact,
        dims=tuple(h.right for h in hops),
        fact_keys=tuple(tuple(h.left_keys) for h in hops),
        dim_keys=tuple(tuple(h.right_keys) for h in hops),
        dim_domains=tuple(h.build_key_domain for h in hops),
        output=tuple(hops[-1].output))
    for f in hoisted:
        cur = FilterNode(cur, f.predicate, cur.output)
    for nd in reversed(upper):
        if isinstance(nd, FilterNode):
            cur = FilterNode(cur, nd.predicate, cur.output)
        else:
            cur = _dc.replace(nd, left=cur)
    return cur


def multijoin_to_ladder(node: MultiJoinNode) -> JoinNode:
    """Reconstruct the exact pairwise ladder a MultiJoinNode fused —
    the executor's full-degrade path and the bit-exactness oracle."""
    acc: PlanNode = node.fact
    out = tuple(node.fact.output)
    ladder = None
    for d, dim in enumerate(node.dims):
        out = out + tuple(dim.output)
        ladder = JoinNode(
            "inner", acc, dim, node.fact_keys[d], node.dim_keys[d],
            None, True, out, distribution=node.distribution,
            build_key_domain=node.dim_domains[d])
        acc = ladder
    return ladder


def star_verdict(root: PlanNode, max_dims: int = 5) -> Optional[str]:
    """EXPLAIN's star-detector verdict for a join-ladder spine: None
    when the spine holds fewer than two joins, else the fuse/decline
    outcome with the stopping reason."""
    got = collect_star(root, max_dims)
    if got is None:
        return None
    _fact, hops, _hoisted, _upper, note = got
    if len(hops) >= 2:
        v = f"fusable k={len(hops)}"
        if note:
            v += f"; stopped: {note}"
        return v
    return f"declined: {note}"


def explain_text(node: PlanNode, indent: int = 0, annotate=None) -> str:
    """EXPLAIN rendering (textual plan like Trino's PlanPrinter).
    `annotate(node) -> str` appends per-node runtime stats
    (EXPLAIN ANALYZE / ExplainAnalyzeOperator's role)."""
    pad = "  " * indent
    if isinstance(node, ScanNode):
        cols = ", ".join(n for n, _ in node.output)
        line = (f"{pad}TableScan[{node.catalog}.{node.schema_name}."
                f"{node.table}] -> [{cols}]")
        if node.predicate is not None:
            line += f", pushdown=[{node.predicate}]"
    elif isinstance(node, FilterNode):
        line = f"{pad}Filter[{node.predicate}]"
    elif isinstance(node, ProjectNode):
        line = f"{pad}Project[{', '.join(n for n, _ in node.output)}]"
    elif isinstance(node, AggregateNode):
        aggs = ", ".join(f"{a.func}({a.out_name})" for a in node.aggs)
        line = (f"{pad}Aggregate[{node.strategy}, keys="
                f"{list(node.group_keys)}, {aggs}]")
    elif isinstance(node, JoinNode):
        line = (f"{pad}Join[{node.kind}, probe={list(node.left_keys)}, "
                f"build={list(node.right_keys)}, "
                f"dist={node.distribution}]")
    elif isinstance(node, MultiJoinNode):
        hops = "; ".join(
            f"{list(fk)}={list(dk)}"
            for fk, dk in zip(node.fact_keys, node.dim_keys))
        line = (f"{pad}MultiJoin[star, k={len(node.dims)}, "
                f"keys=[{hops}], dist={node.distribution}]")
    elif isinstance(node, WindowNode):
        fns = ", ".join(s.func for s in node.specs)
        line = (f"{pad}Window[partition={list(node.partition_by)}, "
                f"order={len(node.order_by)} keys, {fns}]")
    elif isinstance(node, SortNode):
        line = f"{pad}{'TopN' if node.limit else 'Sort'}[{len(node.keys)} keys]"
    elif isinstance(node, LimitNode):
        line = f"{pad}Limit[{node.count}]"
    elif isinstance(node, ValuesNode):
        line = f"{pad}Values[{node.num_rows} rows]"
    elif isinstance(node, SetOpNode):
        line = f"{pad}SetOp[{node.op}]"
    elif isinstance(node, UnnestNode):
        line = (f"{pad}Unnest[col={node.array_col} -> "
                f"{node.element_name}"
                f"{', ordinality' if node.ordinality else ''}]")
    elif isinstance(node, RemoteSourceNode):
        line = f"{pad}RemoteSource[fragment {node.fragment_id}]"
    elif isinstance(node, OutputNode):
        line = f"{pad}Output[{', '.join(node.names)}]"
    elif isinstance(node, TableWriterNode):
        line = (f"{pad}TableWriter[{node.catalog}.{node.schema_name}."
                f"{node.table}, {node.fmt}, partition {node.partition}]")
    elif isinstance(node, TableCommitNode):
        line = (f"{pad}TableCommit[{node.catalog}.{node.schema_name}."
                f"{node.table}]")
    else:
        line = f"{pad}{type(node).__name__}"
    if annotate is not None:
        extra = annotate(node)
        if extra:
            line = f"{line}   {extra}"
    return "\n".join([line] + [explain_text(c, indent + 1, annotate)
                               for c in children(node)])
